"""Client: a process's connection to the control plane + object store.

Role-equivalent to the reference CoreWorker's client surface
(reference: src/ray/core_worker/core_worker.h:295 — Put/Get/Wait/SubmitTask/
CreateActor/SubmitActorTask) minus task execution, which lives in
worker_main.py.  One Client per process (driver or worker).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import exceptions
from . import serialization
from .config import get_config
from .ids import NodeID, ObjectID
from . import object_store
from .object_store import StoreClient
from .rpc import ConnectionLost, RpcClient
from ..devtools.locks import guarded, make_lock

# Head RPCs that are safe to retry on a transient connection hiccup: pure
# reads (no head-side state mutation), so a duplicate delivery is harmless
# (reference: GCS clients retry idempotent RPCs with backoff —
# gcs_rpc_client.h RETRYABLE macros cover the read paths).
IDEMPOTENT_METHODS = frozenset({
    "list_state", "kv_get", "kv_keys", "cluster_resources",
    "available_resources", "store_stats", "object_sizes", "ping",
    "get_actor_by_name", "list_named_actors", "health_ack", "get_log",
    "resolve_actor",
    # Blocking reads: safe to re-issue after a head restart — the restarted
    # head re-learns objects from field-state resync and the re-issued wait
    # blocks until the reseal, giving head-routed gets a bounded pause
    # instead of a hard failure across the restart window.
    "get_objects", "wait_objects",
})
#: Back-compat aliases: the retry shape now lives in config
#: (``rpc_retry_attempts`` / ``rpc_retry_base_s``) and the curve in
#: core/deadline.py — these mirror the defaults for external readers.
IDEMPOTENT_RETRY_ATTEMPTS = 3
IDEMPOTENT_RETRY_BASE_S = 0.05


@guarded
class Client:
    # rtlint RT007 verifies these statically; RT_DEBUG_LOCKS=2 asserts the
    # guards at runtime (devtools.locks).  large_oids/_last_large_free ride
    # _local_lock: they are updated on the same put/free paths that touch
    # the in-process store.
    _RT_GUARDED_BY = {
        "_local_bytes": "_local_lock",
        "large_oids": "_local_lock",
        "_last_large_free": "_local_lock",
        "_bg_exc": "_bg_lock",
        "_put_batch": "_put_batch_lock",
        "_submit_batch": "_submit_batch_lock",
        "_stores": "_stores_lock",
    }
    _RT_UNGUARDED = {
        "rpc": "reconnect swaps in a fresh RpcClient with one reference "
               "store; racing readers use the dying client once more and "
               "retry through call()'s idempotent-retry path",
        "reconnect_refused": "monotonic None->reason publication from the "
                             "reconnect path (under _reconnect_lock); the "
                             "worker's reconnect thread polls it and a "
                             "stale None read just retries once more",
        "trace_sample_rate": "head-config publication at (re)register "
                             "(init, then under _reconnect_lock); tracing "
                             "readers tolerate a stale value for one "
                             "sampling decision",
    }

    def __init__(
        self,
        head_addr: str,
        kind: str,
        worker_id: Optional[bytes] = None,
        node_id: Optional[bytes] = None,
        pid: int = 0,
        session: Optional[str] = None,
        log_path: Optional[str] = None,
        peer_addr: Optional[str] = None,
    ):
        from . import schema as wire_schema

        self.head_addr = head_addr
        host, port = head_addr.rsplit(":", 1)
        self.rpc = RpcClient(host, int(port), name=f"{kind}-rpc")
        # Re-registration identity for head-restart reconnects: the SAME
        # worker identity must be adopted by the restarted head (field-state
        # resync), so the original register body's fields are retained.
        self._reg_info: Dict[str, Any] = {
            "kind": kind, "pid": pid, "worker_id": worker_id,
            "node_id": node_id, "log_path": log_path, "peer_addr": peer_addr,
        }
        # Populated by the owner process (worker_main) with a callable
        # returning the live field state (hosted actor + incarnation) to
        # carry on a reconnect register; None for drivers.
        self.resync_payload = None
        # Reconnect outcome channel for the owner's reconnect loop: set to a
        # reason string when the head explicitly refused to adopt this
        # process (stale incarnation, dead actor) — retrying is pointless
        # and the process should exit.
        self.reconnect_refused: Optional[str] = None
        # Post-reconnect hook (owner-installed): replay buffered reports,
        # re-arm process-level state.  Runs after the swap, outside locks.
        self.on_reconnected = None
        body: Dict[str, Any] = {
            "kind": kind, "pid": pid,
            "protocol": wire_schema.PROTOCOL_VERSION,
        }
        if peer_addr:
            # Worker-plane endpoint: the head hands this address to peers
            # for direct actor calls and task leases.
            body["peer_addr"] = peer_addr
        if log_path:
            # Registered in the head's cluster log index (retained past
            # process death) so `get_log` can serve this process's output.
            body["log_path"] = log_path
        if kind == "driver" and os.environ.get("RT_FORCE_PROXY_DRIVER") == "1":
            # Opt into the off-host proxy path explicitly (tests; also
            # useful when the driver host has no usable /dev/shm).
            body["force_proxy"] = True
        if worker_id is not None:
            body["worker_id"] = worker_id
        if node_id is not None:
            body["node_id"] = node_id
        reply = self.rpc.call("register", body)
        # Writes go under this process's *node* store session (worker
        # processes on non-head nodes pass it in); the head session is the
        # default for drivers/head-node processes.
        self.session: str = session or reply["session"]
        self.node_id: Optional[NodeID] = (
            NodeID(node_id) if node_id else
            (NodeID(reply["node_id"]) if reply.get("node_id") else None)
        )
        # Proxy mode (off-host driver, the Ray Client role): no local shm
        # attach — puts upload to the head, gets pull over TCP.  Pulled
        # copies land in a private local session namespace so a same-host
        # proxy (tests, RT_FORCE_PROXY_DRIVER) never clobbers the cluster
        # session's segments.
        self.proxy: bool = bool(reply.get("proxy"))
        if self.proxy:
            self.session = f"{self.session}-proxy{os.getpid()}"
        # Head-configured root-trace sampling rate (util/tracing.py reads
        # it at every trace root): one knob on the head governs the whole
        # cluster.  None -> fall back to this process's local config.
        self.trace_sample_rate = reply.get("trace_sample_rate")
        self.kind = kind
        # Per-session store clients: created lazily from whatever thread
        # first touches a session (user threads, push handlers on the rpc
        # loop, the free flusher).
        self._stores: Dict[str, StoreClient] = {}
        self._stores_lock = make_lock("client.stores")
        # In-process store for small objects this process owns or has read
        # (packed blobs, LRU-bounded).  The analog of the reference's
        # CoreWorkerMemoryStore (src/ray/core_worker/store_provider/
        # memory_store/memory_store.h:43): puts and repeated gets of small
        # objects never pay a control-plane round trip.
        self._local: "OrderedDict[ObjectID, bytes]" = OrderedDict()
        self._local_bytes = 0
        self._local_cap = get_config().local_store_max_bytes
        self._local_lock = make_lock("client.local_store")
        # In-flight fire-and-forget RPCs (registrations, submissions): a
        # bounded pipeline so submission throughput isn't gated on one
        # round trip per call (reference: task submission is async; errors
        # surface on the returned ref).
        self._bg_futs: deque = deque()
        self._bg_lock = make_lock("client.bg_pipeline")
        self._bg_exc: Optional[BaseException] = None
        # Buffered inline-object registrations (flushed as one RPC before
        # any other outbound call — see _flush_put_batch).
        self._put_batch: List[dict] = []
        self._put_batch_lock = make_lock("client.put_batch")
        # Buffered fire-and-forget calls (see call_batched).
        self._submit_batch: List[dict] = []
        self._submit_batch_lock = make_lock("client.submit_batch")
        # Function-table keys this process has already exported (api._export).
        self.exported_keys: set = set()
        # Large (shm) objects this process put, raw id -> size: their frees
        # flush immediately instead of batching (so multi-MiB segments return
        # to the store's warm pool promptly), and a driver reconnecting to a
        # RESTARTED head re-registers them from this map so the rebuilt
        # object directory can answer for its puts.
        self.large_oids: Dict[bytes, int] = {}
        self._last_large_free = 0.0
        self._sub_handlers: Dict[str, List[Callable]] = {}
        self._sub_lock = make_lock("client.pubsub")
        # Connections to other nodes' object-plane (pull) servers.
        self._pull_conns: Dict[str, RpcClient] = {}
        self._bulk_conns: Dict[str, tuple] = {}
        self._pull_lock = make_lock("client.pull_conns")
        self.rpc.on_push("pubsub", self._on_pubsub)
        self.rpc.on_push("object_free", self._on_object_free)
        # Peer dataplane: direct actor calls + leased task slots (proxy
        # drivers excluded — no peer reachability guarantees off-host).
        self._dataplane = None
        cfg = get_config()
        if not self.proxy and kind in ("driver", "worker") \
                and (cfg.direct_calls or cfg.task_leases):
            from .dataplane import Dataplane

            self._dataplane = Dataplane(self)
        # Free-queue flusher: ObjectRef.__del__ only appends + signals (it
        # may run from cyclic GC inside a client critical section, so it
        # must never take client locks itself); this thread does the RPCs.
        self._reconnect_lock = make_lock("client.reconnect")
        self._free_flusher = threading.Thread(
            target=self._free_flush_loop, daemon=True, name="free-flusher"
        )
        self._free_flusher.start()

    def _free_flush_loop(self):
        from . import object_ref as oref
        from .context import ctx

        while not self.rpc.closed:
            oref.flush_wanted.wait(timeout=0.5)
            oref.flush_wanted.clear()
            if self.rpc.closed:
                return
            if ctx.client is not None and ctx.client is not self:
                return  # superseded by a newer session's client
            try:
                oref._flush_free_queue(background=True)
                # Span plane: drain the process-local span ring into one
                # batched span_batch entry — the existing background-report
                # cadence IS the span flush cadence (and while headless the
                # batch buffers for replay like task_done reports).
                from ray_tpu.util import gangrec as _gangrec
                from ray_tpu.util import steprec as _steprec
                from ray_tpu.util import tracing as _tracing

                _tracing.flush_spans(self)
                # Flight-recorder plane: engine step records and gang round
                # records batch-flush on the same cadence (and dump their
                # black-box sidecars so a SIGKILL still leaves the last N
                # steps/rounds on disk).
                _steprec.flush_steps(self)
                _gangrec.flush_rounds(self)
                # Safety net: batched calls must not sit forever in a driver
                # that stops making client calls (e.g. waits on side effects).
                self._flush_submit_batch()
                self._flush_put_batch()
                if self._dataplane is not None:
                    # Lease renew/idle-return, stale-queue flush, retired
                    # connection teardown.
                    self._dataplane.maintain()
            except Exception:
                pass

    # -- stores ----------------------------------------------------------------

    def store(self, session: Optional[str] = None) -> StoreClient:
        session = session or self.session
        with self._stores_lock:
            st = self._stores.get(session)
            if st is None:
                st = self._stores[session] = StoreClient(session)
            return st

    def _stores_snapshot(self) -> List[StoreClient]:
        with self._stores_lock:
            return list(self._stores.values())

    def _on_object_free(self, body):
        dirty: List[bytes] = []
        if self._dataplane is not None:
            self._dataplane.drop_results(list(body.get("object_ids", [])))
        for raw in body.get("object_ids", []):
            oid = ObjectID(raw)
            self._local_drop(oid)
            clean = True
            for st in self._stores_snapshot():
                had = oid in st._attached
                if not st.detach(oid):
                    clean = False
                elif had and self.proxy:
                    # Proxy-pulled copies live in this process's private
                    # session namespace: no node daemon owns the file, so
                    # unlink it here or the driver host's shm grows without
                    # bound.
                    from .object_store import _seg_path

                    try:
                        os.unlink(_seg_path(st._session, oid))
                    except OSError:
                        pass
            if not clean:
                dirty.append(raw)
        token = body.get("ack_token")
        if token is not None:
            # Runs on the rpc loop thread: fire-and-forget (a blocking call
            # here would deadlock the loop).  The head pools the segments
            # only after this ack; dirty ids (live zero-copy views in this
            # process) are unlinked instead so the views stay valid.
            try:
                self.rpc.call_async(
                    "object_free_ack", {"token": token, "dirty": dirty}
                )
            except Exception:
                pass

    # -- in-process store / background pipeline --------------------------------

    def _local_put(self, oid: ObjectID, blob: bytes):
        with self._local_lock:
            prev = self._local.pop(oid, None)
            if prev is not None:
                self._local_bytes -= len(prev)
            self._local[oid] = blob
            self._local_bytes += len(blob)
            while self._local_bytes > self._local_cap and self._local:
                _, victim = self._local.popitem(last=False)
                self._local_bytes -= len(victim)

    def _local_get(self, oid: ObjectID) -> Optional[bytes]:
        with self._local_lock:
            blob = self._local.get(oid)
            if blob is not None:
                self._local.move_to_end(oid)
            return blob

    def _local_drop(self, oid: ObjectID):
        with self._local_lock:
            blob = self._local.pop(oid, None)
            if blob is not None:
                self._local_bytes -= len(blob)

    def call_bg(self, method: str, body: Any):
        """Fire an RPC without waiting for the reply.  Ordering vs later
        calls on this client is preserved (single connection, FIFO).  Errors
        surface on the next synchronous call; a bounded in-flight window
        applies backpressure when the head falls behind."""
        self._flush_put_batch()
        self._flush_submit_batch()
        self._call_bg_raw(method, body)

    def _call_bg_raw(self, method: str, body: Any):
        # Reap/wait OUTSIDE the lock: the backpressure wait can block up
        # to 60s, and check_bg (every sync call) takes _bg_lock — holding
        # it here would stall the whole client behind one backlogged RPC.
        done_futs: List[Any] = []
        wait_fut = None
        with self._bg_lock:
            while self._bg_futs and self._bg_futs[0].done():
                done_futs.append(self._bg_futs.popleft())
            if len(self._bg_futs) >= 1000:
                wait_fut = self._bg_futs.popleft()
        for fut in done_futs:
            self._note_bg_exc(fut)
        if wait_fut is not None:
            self._note_bg_exc(wait_fut, wait=True)
        with self._bg_lock:
            self._bg_futs.append(self.rpc.call_async(method, body))

    def _flush_put_batch(self):
        """Send buffered inline-object registrations as one RPC.  Flushed
        before ANY other outbound call so no message that could reference a
        buffered object ever overtakes its registration.  While headless
        (lost head connection, reconnect pending) the batch stays buffered:
        registrations queue and replay after re-register instead of being
        dropped into a dead socket."""
        with self._put_batch_lock:
            if self.rpc.closed:
                return
            batch, self._put_batch = self._put_batch, []
        if batch:
            self._call_bg_raw("put_object_batch", {"objects": batch})

    def call_batched(self, method: str, body: dict):
        """Buffer a fire-and-forget call; bursts flush as ONE head RPC
        (head message processing, not wire latency, bounds control-plane
        throughput).  Order within the mixed batch is preserved, and every
        sync/bg call flushes it first, so batching never reorders."""
        self._flush_put_batch()  # registrations precede referencing bodies
        with self._submit_batch_lock:
            self._submit_batch.append({"method": method, "body": body})
            n = len(self._submit_batch)
        if n >= 64:
            self._flush_submit_batch()

    def _flush_submit_batch(self):
        with self._submit_batch_lock:
            # Headless: hold the batch (task_done reports, submissions) for
            # replay after reconnect — a worker finishing tasks during a
            # head restart must not lose its completion reports.
            if self.rpc.closed:
                return
            batch, self._submit_batch = self._submit_batch, []
        if batch:
            self._call_bg_raw("batch", {"entries": batch})

    def _note_bg_exc(self, fut, wait: bool = False):
        """Record a background failure.  Never called with _bg_lock held —
        the wait=True path blocks on the head for up to 60s."""
        try:
            if wait:
                fut.result(timeout=60)
                exc = None
            else:
                exc = fut.exception()
        except BaseException as e:  # noqa: BLE001
            exc = e
        if exc is not None and not isinstance(exc, ConnectionLost):
            with self._bg_lock:
                self._bg_exc = exc

    def check_bg(self):
        """Raise (once) a deferred error from the background pipeline."""
        with self._bg_lock:
            exc, self._bg_exc = self._bg_exc, None
        if exc is not None:
            raise exc

    # -- task/actor submission (dataplane routing) -----------------------------

    def submit_task(self, spec: dict) -> None:
        """Submit a stateless task: a leased direct slot when one is held
        (peer plane, no head traffic), else the head path — which also
        primes lease acquisition for the next burst."""
        dp = self._dataplane
        if dp is not None:
            dp.ensure_args_shared(spec)
            if dp.submit_task(spec):
                return
        self.call_batched("submit_task", spec)

    def submit_actor_task(self, spec: dict) -> None:
        """Submit an actor call: peer-direct once the actor's address is
        resolved (and the switch is order-safe), else head-mediated."""
        dp = self._dataplane
        if dp is not None:
            dp.ensure_args_shared(spec)
            if dp.submit_actor_task(spec):
                return
            dp.note_head_actor_call(spec["actor_id"])
        self.call_batched("submit_actor_task", spec)

    def prepare_actor_route(self, raw_actor_id: bytes) -> None:
        """Register interest in an actor's peer route at creation time (the
        ALIVE broadcast then pre-dials during creation dispatch)."""
        if self._dataplane is not None:
            self._dataplane.prepare_actor_route(raw_actor_id)

    def ensure_shared(self, raw: bytes) -> None:
        """A ref is crossing a process boundary: make sure the head can
        answer for it even if its value only lives in this process's
        direct-result cache."""
        if self._dataplane is not None:
            self._dataplane.ensure_shared(raw)

    def ensure_args_shared(self, spec: dict) -> None:
        """Same, for every arg id of a spec that bypasses the routed
        submission paths (e.g. actor creation tasks)."""
        if self._dataplane is not None:
            self._dataplane.ensure_args_shared(spec)

    def cancel_task(self, task_raw: bytes, force: bool = False):
        if self._dataplane is not None \
                and self._dataplane.cancel_task(task_raw, force):
            return {"cancelled": True}
        return self.call("cancel_task",
                         {"task_id": task_raw, "force": force})

    def drain_bg(self, timeout: float = 30.0):
        """Block until all fired background RPCs have been acknowledged."""
        self._flush_put_batch()
        self._flush_submit_batch()
        with self._bg_lock:
            futs, self._bg_futs = list(self._bg_futs), deque()
        for f in futs:
            try:
                f.result(timeout=timeout)
            except BaseException as e:  # noqa: BLE001
                if not isinstance(e, ConnectionLost):
                    with self._bg_lock:
                        self._bg_exc = e
        self.check_bg()

    # -- objects ---------------------------------------------------------------

    def put(self, value: Any) -> ObjectID:
        oid = ObjectID.from_random()
        self.put_with_id(oid, value)
        return oid

    def put_with_id(self, oid: ObjectID, value: Any) -> int:
        cfg = get_config()
        _t0 = time.perf_counter()
        meta, buffers = serialization.serialize(value)
        size = serialization.packed_size(meta, buffers)
        # Contention accounting (doctor --object-plane): the large-put wall
        # splits into serialize (here) / alloc / first_touch (StoreClient)
        # / copy (pack_into below).  Inline puts skip the bookkeeping — two
        # histogram observes would be real overhead on a ~100us path.
        _large = size > cfg.inline_object_max_bytes and not self.proxy
        if _large:
            object_store.note_put_stage(
                "serialize", time.perf_counter() - _t0, size)
        if size <= cfg.inline_object_max_bytes:
            blob = bytearray(size)
            serialization.pack_into(meta, buffers, memoryview(blob))
            blob = bytes(blob)
            self._local_put(oid, blob)
            with self._put_batch_lock:
                self._put_batch.append(
                    {"object_id": oid.binary(), "inline": blob}
                )
                n = len(self._put_batch)
            if n >= 64:
                self._flush_put_batch()
        elif self.proxy:
            # Off-host driver: no local shm store the cluster can read —
            # upload into the head's store in message-sized chunks
            # (reference: util/client/dataclient.py chunked put stream).
            blob = bytearray(size)
            serialization.pack_into(meta, buffers, memoryview(blob))
            chunk = 4 << 20
            futs = []
            for off in range(0, size, chunk):
                part = bytes(blob[off:off + chunk])
                futs.append(self.rpc.call_async("proxy_put", {
                    "object_id": oid.binary(), "total": size,
                    "offset": off, "data": part,
                    "done": off + chunk >= size,
                }))
                while len(futs) > 4:
                    futs.pop(0).result(timeout=120)
            for f in futs:
                f.result(timeout=120)
        else:
            # If this process freed large objects moments ago, their warm
            # segments are on their way to the pool (free -> detach-ack ->
            # pool, a few ms): a short wait claims warm pages instead of
            # paying cold first-touch faults.
            with self._local_lock:
                recent = time.monotonic() - self._last_large_free < 0.5
            wait = 0.06 if recent else 0.0
            buf = self.store().create(oid, size, wait_pool_s=wait)
            _t1 = time.perf_counter()
            serialization.pack_into(meta, buffers, buf)
            object_store.note_put_stage(
                "copy", time.perf_counter() - _t1, size)
            with self._local_lock:
                self.large_oids[oid.binary()] = size
            # Registration rides the put batch (same-connection FIFO keeps
            # it ahead of any message referencing the object) — and, while
            # headless, it queues for replay instead of vanishing into a
            # dead socket.
            with self._put_batch_lock:
                self._put_batch.append(
                    {"object_id": oid.binary(), "size": size,
                     "node_id": self.node_id.binary()}
                )
            _t2 = time.perf_counter()
            self._flush_put_batch()
            object_store.note_put_stage(
                "register", time.perf_counter() - _t2, 0)
        return size

    @contextlib.contextmanager
    def _maybe_blocked(self):
        """Tell the head this worker is parked in a blocking get/wait so its
        task's resources can be released (and a replacement worker spawned) —
        without this, nested gets deeper than the worker-pool cap deadlock
        (reference: raylet releases the CPU lease for workers blocked in
        ray.get).  Actor tasks hold no pool resources, so they skip it."""
        from .context import ctx

        tid = ctx.current_task_id
        if self.kind != "worker" or tid is None or ctx.current_actor_id is not None:
            yield
            return
        try:
            self.rpc.call("task_blocked", {"task_id": tid.binary()})
        except Exception:
            pass
        try:
            yield
        finally:
            try:
                self.rpc.call("task_unblocked", {"task_id": tid.binary()})
            except Exception:
                pass

    def get_raw(self, object_ids: Sequence[ObjectID], timeout: float = -1.0):
        """Fetch wire descriptors for objects (blocking until sealed)."""
        self._flush_put_batch()
        self._flush_submit_batch()
        with self._maybe_blocked():
            # Through call(): get_objects is idempotent, so a head-restart
            # window retries (with reconnects between attempts) instead of
            # surfacing the first ConnectionLost — the bounded pause.
            reply = self.call(
                "get_objects",
                {"object_ids": [o.binary() for o in object_ids], "timeout": timeout},
                timeout=None if timeout < 0 else timeout + 30,
            )
        return reply["objects"]

    def get(self, refs: Sequence, timeout: float = -1.0) -> List[Any]:
        self.check_bg()
        object_ids = [r.object_id for r in refs]
        dp = self._dataplane
        if dp is not None:
            # Flush staged peer submissions, then block on their replies —
            # no head involvement for the whole get when every ref is a
            # direct result.  The direct wait consumes from the SAME
            # timeout budget the head fetch below gets (never double it).
            t0 = time.monotonic()
            dp.flush_pending()
            dp.await_calls([o.binary() for o in object_ids], timeout)
            if timeout >= 0:
                timeout = max(0.0, timeout - (time.monotonic() - t0))
        # In-process store first: objects this process put or already read
        # resolve without a control-plane round trip.
        local: Dict[int, bytes] = {}
        direct: Dict[int, dict] = {}
        missing: List[ObjectID] = []
        for i, oid in enumerate(object_ids):
            if dp is not None:
                d = dp.result_desc(oid.binary())
                if d is not None:
                    direct[i] = d
                    continue
            blob = self._local_get(oid)
            if blob is not None:
                local[i] = blob
            else:
                missing.append(oid)
        descs = iter(self.get_raw(missing, timeout) if missing else ())
        out = []
        for i, oid in enumerate(object_ids):
            if i in direct:
                try:
                    out.append(self._materialize(oid, direct[i]))
                except exceptions.ObjectReconstructionFailedError:
                    raise
                except exceptions.ObjectLostError:
                    out.append(self._recover_and_get(oid, timeout))
                continue
            if i in local:
                out.append(serialization.unpack(local[i]))
                continue
            desc = next(descs)
            if desc.get("timeout"):
                raise exceptions.GetTimeoutError(
                    f"ray_tpu.get timed out after {timeout}s on {oid}"
                )
            inline = desc.get("inline")
            if inline is not None and desc.get("error") is None:
                self._local_put(oid, inline)
            try:
                out.append(self._materialize(oid, desc))
            except exceptions.ObjectReconstructionFailedError:
                raise
            except exceptions.ObjectLostError:
                out.append(self._recover_and_get(oid, timeout))
        return out

    def _recover_and_get(self, oid: ObjectID, timeout: float):
        """Every known copy of the object is gone: ask the head to recompute
        it from lineage, then wait for the re-seal and re-read (reference:
        object_recovery_manager.h:90)."""
        from . import deadline as _dl

        deadline = None if timeout < 0 else _dl.Deadline.after(timeout)
        # The sole-copy node may be dead but not yet declared (its head
        # connection can linger); back off between attempts so the health
        # prober has time to reap it and the head drops the stale location.
        backoff = _dl.BackoffPolicy(base_s=0.5, multiplier=2.0, cap_s=2.0,
                                    jitter=0.0)
        for attempt in range(3):
            if attempt:
                backoff.sleep(attempt, deadline)
            self.call("reconstruct_object", {"object_id": oid.binary()})
            remaining = (
                -1.0 if deadline is None
                else max(0.0, deadline.remaining())
            )
            desc = self.get_raw([oid], remaining)[0]
            if desc.get("timeout"):
                raise exceptions.GetTimeoutError(
                    f"ray_tpu.get timed out awaiting reconstruction of {oid}"
                )
            try:
                return self._materialize(oid, desc)
            except exceptions.ObjectReconstructionFailedError:
                raise
            except exceptions.ObjectLostError:
                continue  # lost again mid-recovery (another node died)
        raise exceptions.ObjectLostError(
            f"object {oid} kept vanishing during reconstruction"
        )

    def _materialize(self, oid: ObjectID, desc: dict) -> Any:
        if desc.get("error") is not None:
            raise serialization.unpack(desc["error"])
        if desc.get("inline") is not None:
            return serialization.unpack(desc["inline"])
        loc = desc.get("node_id")
        if self.proxy:
            # Off-host driver: every stored object is remote by definition;
            # pull it over the owning node's object-plane endpoints.
            view = self._pull_remote(oid, desc)
            return serialization.unpack(view)
        if (loc is not None and self.node_id is not None
                and loc != self.node_id.binary()):
            # The object lives on another node: fetch it over that node's
            # object-plane server into our local store (reference:
            # object_manager.h:117 chunked pull + local plasma copy).
            view = self._pull_remote(oid, desc)
            return serialization.unpack(view)
        view = self.store(desc["session"]).get(oid, timeout=2.0)
        if view is None:
            # Segment may have been spilled to disk; ask the store daemon to
            # restore it, then retry the attach.
            if self.rpc.call(
                "restore_object", {"object_id": oid.binary()}
            ).get("ok"):
                view = self.store(desc["session"]).get(oid, timeout=2.0)
        if view is None:
            raise exceptions.ObjectLostError(
                f"object {oid} location lost (node died?)"
            )
        return serialization.unpack(view)

    # -- inter-node transfer ---------------------------------------------------

    def _pull_conn(self, addr: str) -> RpcClient:
        with self._pull_lock:
            conn = self._pull_conns.get(addr)
            if conn is None or conn.closed:
                host, port = addr.rsplit(":", 1)
                conn = RpcClient(host, int(port), name="object-pull")
                self._pull_conns[addr] = conn
            return conn

    def _pull_remote(self, oid: ObjectID, desc: dict) -> memoryview:
        from .node_main import PULL_CHUNK_BYTES

        addr = desc.get("addr")
        if not addr:
            raise exceptions.ObjectLostError(
                f"object {oid}: owner node has no object-plane address"
            )
        local = self.store()
        existing = local.get(oid)
        if existing is not None:  # already pulled by this process earlier
            return existing
        size = desc["size"]
        buf, commit, abort = local.create_staged(oid, size)
        if size >= (8 << 20):
            # Fault in backing pages in parallel before the transfer: the
            # recv_into loop otherwise pays first-touch faults serially,
            # one page per 4 KiB of stream.
            from ray_tpu import _native

            _native.prefault(buf)
        bulk_addr = desc.get("bulk_addr")
        if bulk_addr:
            try:
                self._bulk_pull(bulk_addr, oid, buf, size)
                return self._commit_pull(oid, size, commit)
            except exceptions.ObjectLostError:
                abort()
                raise
            except Exception:
                pass  # bulk channel unavailable: fall back to chunked RPC
        try:
            # Pipelined chunk window: several chunk requests in flight on the
            # one connection so the transfer overlaps server read, wire time
            # and local memcpy (reference: object_manager.h:63 splits objects
            # into chunks and streams them concurrently).
            from ray_tpu import _native

            rpc = self._pull_conn(addr)
            window = 8
            futs: Dict[int, Any] = {}
            next_off = 0

            def fire():
                nonlocal next_off
                while next_off < size and len(futs) < window:
                    futs[next_off] = rpc.call_async(
                        "pull_object",
                        {"object_id": oid.binary(), "offset": next_off,
                         "max_bytes": PULL_CHUNK_BYTES},
                    )
                    next_off += PULL_CHUNK_BYTES

            fire()
            while futs:
                off = min(futs)
                reply = futs.pop(off).result(timeout=120.0)
                if not reply.get("found"):
                    raise exceptions.ObjectLostError(
                        f"object {oid} vanished from {addr} mid-pull"
                    )
                data = reply["data"]
                want = min(PULL_CHUNK_BYTES, size - off)
                if len(data) != want:
                    raise exceptions.ObjectLostError(
                        f"object {oid}: short chunk at offset {off} from {addr}"
                    )
                if len(data) >= (1 << 20):
                    _native.copy(buf[off:off + len(data)], data)
                else:
                    buf[off:off + len(data)] = data
                fire()
        except Exception:
            abort()
            raise
        return self._commit_pull(oid, size, commit)

    def _commit_pull(self, oid: ObjectID, size: int, commit) -> memoryview:
        view = commit()
        # Register the new copy: same-node readers now attach via shm, and
        # the node's store daemon takes accounting ownership.  `from_pull`
        # lets the head reject (and reclaim) the copy if the object's last
        # reference was dropped mid-pull — resurrecting a freed record would
        # leak the segment with no owner left to decref it.  Proxy drivers
        # skip registration: their private copy is not a cluster location.
        if self.node_id is not None:
            try:
                self.rpc.call(
                    "put_object",
                    {"object_id": oid.binary(), "size": size,
                     "node_id": self.node_id.binary(), "from_pull": True},
                )
            except Exception:
                pass
        return view

    def _bulk_conn(self, addr: str):
        import socket

        with self._pull_lock:
            entry = self._bulk_conns.get(addr)
        if entry is not None:
            return entry
        # Connect outside the lock: a 30s timeout on an unreachable node
        # must not stall other threads' pull-connection lookups.
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        entry = (sock, make_lock("client.bulk_conn"))
        with self._pull_lock:
            racer = self._bulk_conns.get(addr)
            if racer is not None:
                sock.close()
                return racer
            self._bulk_conns[addr] = entry
        return entry

    def _bulk_pull(self, addr: str, oid: ObjectID, buf: memoryview, size: int):
        """Raw-TCP transfer into the staged segment: request, then
        recv_into() the mmap directly — no framing or intermediate copies
        (server side is sendfile; see node_main.BulkServer)."""
        import struct

        from .node_main import BULK_NOT_FOUND

        sock, lock = self._bulk_conn(addr)
        try:
            with lock:
                sock.sendall(oid.binary() + struct.pack("<QQ", 0, size))
                hdr = b""
                while len(hdr) < 8:
                    part = sock.recv(8 - len(hdr))
                    if not part:
                        raise ConnectionError("bulk channel closed")
                    hdr += part
                (n,) = struct.unpack("<Q", hdr)
                if n == BULK_NOT_FOUND:
                    raise exceptions.ObjectLostError(
                        f"object {oid} vanished from {addr} mid-pull"
                    )
                if n != size:
                    raise exceptions.ObjectLostError(
                        f"object {oid}: bulk size mismatch ({n} != {size})"
                    )
                got = 0
                while got < n:
                    r = sock.recv_into(buf[got:], n - got)
                    if r == 0:
                        raise ConnectionError("bulk channel closed mid-body")
                    got += r
        except BaseException:
            # Any failure leaves undrained body bytes on the stream — the
            # connection is desynced and must not be reused (a poisoned
            # socket would parse stale body bytes as the next length header,
            # and the server would sit in sendfile holding a pin).
            with self._pull_lock:
                if self._bulk_conns.get(addr) is not None \
                        and self._bulk_conns[addr][0] is sock:
                    self._bulk_conns.pop(addr, None)
            try:
                sock.close()
            except OSError:
                pass
            raise

    def wait(self, refs: Sequence, num_returns: int, timeout: float):
        self._flush_put_batch()
        self._flush_submit_batch()
        raws = [r.object_id.binary() for r in refs]
        dp = self._dataplane
        if dp is not None:
            dp.flush_pending()
        if dp is None:
            ready_set = self._wait_head(raws, num_returns, timeout)
        else:
            # Mixed readiness sources: direct-call results resolve locally
            # (their completion never touches the head), everything else
            # via the head's wait.  Pure-direct waits make no head RPC at
            # all; mixed waits slice the head wait so local completions
            # can satisfy num_returns early.
            deadline = None if timeout < 0 else time.monotonic() + timeout
            head_ready: set = set()
            while True:
                local_ready, events, head_raws = dp.wait_split(raws)
                ready_set = local_ready | head_ready
                if len(ready_set) >= num_returns:
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                head_pending = [raw for raw in head_raws
                                if raw not in head_ready]
                if remaining is not None and remaining <= 0:
                    # Budget exhausted — including the pure-poll timeout=0
                    # case, which must still ask the head once: breaking
                    # without a poll reports already-sealed head objects
                    # as not-ready forever.
                    if head_pending:
                        head_ready |= self._wait_head(
                            head_pending,
                            min(max(num_returns - len(ready_set), 1),
                                len(head_pending)),
                            0.0,
                        )
                        local_ready, _, _ = dp.wait_split(raws)
                        ready_set = local_ready | head_ready
                    break
                if head_pending:
                    slice_t = 0.05 if events else remaining
                    if remaining is not None and slice_t is not None:
                        slice_t = min(slice_t, remaining)
                    head_ready |= self._wait_head(
                        head_pending,
                        min(max(num_returns - len(ready_set), 1),
                            len(head_pending)),
                        -1.0 if slice_t is None else slice_t,
                    )
                    if not events:
                        # The head wait consumed the whole budget: final.
                        local_ready, _, _ = dp.wait_split(raws)
                        ready_set = local_ready | head_ready
                        break
                elif events:
                    step = (0.02 if remaining is None
                            else max(0.001, min(0.02, remaining)))
                    events[0].wait(step)
                else:
                    break
        ready = [r for r in refs if r.object_id.binary() in ready_set]
        not_ready = [r for r in refs if r.object_id.binary() not in ready_set]
        return ready, not_ready

    def _wait_head(self, raws: List[bytes], num_returns: int,
                   timeout: float) -> set:
        with self._maybe_blocked():
            # Through call(): wait_objects is idempotent — rides the
            # head-restart retry window like get_objects.
            reply = self.call(
                "wait_objects",
                {
                    "object_ids": raws,
                    "num_returns": num_returns,
                    "timeout": timeout,
                },
                timeout=None if timeout < 0 else timeout + 30,
            )
        return set(reply["ready"])

    def _note_frees(self, raw_ids: List[bytes]):
        """Local-store drops + large-segment free timestamps for a free
        batch, under one _local_lock pass (the free flusher thread and
        user threads both reach here)."""
        with self._local_lock:
            for raw in raw_ids:
                blob = self._local.pop(ObjectID(raw), None)
                if blob is not None:
                    self._local_bytes -= len(blob)
                if self.large_oids.pop(raw, None) is not None:
                    self._last_large_free = time.monotonic()

    def free_objects(self, raw_ids: List[bytes]):
        self._note_frees(raw_ids)
        if self._dataplane is not None:
            # Drop cached direct results; defer frees of args pinned by
            # in-flight direct calls (released at call completion).
            raw_ids = self._dataplane.intercept_frees(raw_ids)
            if not raw_ids:
                return
        # Flush buffered registrations/submissions first: freeing an object
        # whose registration is still batched would hit an unknown record
        # head-side and the late registration would resurrect it as a leak.
        self._flush_put_batch()
        self._flush_submit_batch()
        self.rpc.call("free_objects", {"object_ids": raw_ids})

    def free_objects_bg(self, raw_ids: List[bytes]):
        """Pipelined free for the ObjectRef GC flusher: local drops +
        dataplane interception, then a fire-and-forget head RPC."""
        self._note_frees(raw_ids)
        if self._dataplane is not None:
            raw_ids = self._dataplane.intercept_frees(raw_ids)
            if not raw_ids:
                return
        self.call_bg("free_objects", {"object_ids": raw_ids})

    def add_reference(self, raw_id: bytes):
        try:
            self.rpc.call("add_object_ref", {"object_ids": [raw_id]})
        except Exception:
            pass

    def next_stream_item(self, task_id: bytes, index: int) -> dict:
        if self._dataplane is not None:
            # Direct streaming tasks serve their items straight from the
            # executing worker (peer_next_stream_item).
            reply = self._dataplane.next_stream_item(task_id, index)
            if reply is not None:
                return reply
        with self._maybe_blocked():
            # Streams have no per-item budget: the producer paces the
            # consumer, so this read legitimately waits forever.
            return self.rpc.call(
                "next_stream_item", {"task_id": task_id, "index": index},
                timeout=None,
            )

    # -- KV --------------------------------------------------------------------

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        return self.rpc.call(
            "kv_put", {"key": key, "value": value, "overwrite": overwrite}
        )["added"]

    def kv_get(self, key: str) -> Optional[bytes]:
        # Via call(): kv_get is in IDEMPOTENT_METHODS, so transient
        # connection errors retry instead of failing rendezvous/polling.
        return self.call("kv_get", {"key": key})["value"]

    def kv_del(self, key: str) -> bool:
        return self.rpc.call("kv_del", {"key": key})["deleted"]

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self.call("kv_keys", {"prefix": prefix})["keys"]

    # -- pubsub ----------------------------------------------------------------

    def _on_pubsub(self, body):
        with self._sub_lock:
            handlers = list(self._sub_handlers.get(body["topic"], ()))
        for fn in handlers:
            try:
                fn(body["data"])
            except Exception:
                import traceback

                traceback.print_exc()

    def subscribe(self, topic: str, handler: Callable[[Any], None]):
        with self._sub_lock:
            self._sub_handlers.setdefault(topic, []).append(handler)
        self.rpc.call("subscribe", {"topic": topic})

    def unsubscribe(self, topic: str, handler: Callable[[Any], None]) -> None:
        """Drop a local handler registered via subscribe().  The server-side
        topic subscription stays (other handlers may share it); a process
        with zero handlers simply ignores the pushes."""
        with self._sub_lock:
            handlers = self._sub_handlers.get(topic)
            if handlers and handler in handlers:
                handlers.remove(handler)

    def publish(self, topic: str, data: Any):
        self.rpc.call("publish", {"topic": topic, "data": data})

    # -- passthrough -----------------------------------------------------------

    def call(self, method: str, body=None, timeout: Optional[float] = 60.0):
        self.check_bg()
        self._flush_put_batch()
        self._flush_submit_batch()
        # getattr: synthetic/partial clients (tests, tooling) may lack the
        # dataplane field entirely.
        dp = getattr(self, "_dataplane", None)
        if dp is not None:
            # Cross-plane ordering: staged peer submissions flush before
            # any synchronous control-plane call (kill_actor after a burst
            # of casts must land after them, matching head-batch flushing).
            dp.flush_pending()
        if method not in IDEMPOTENT_METHODS:
            try:
                return self.rpc.call(method, body, timeout=timeout)
            except ConnectionLost as e:
                # A mutating call interrupted by connection loss cannot be
                # replayed safely (the head may or may not have applied it).
                # Heal the connection for the caller's NEXT call, then fail
                # typed so the caller knows to resubmit this one.
                try:
                    self._try_reconnect()
                except Exception:
                    pass
                raise exceptions.HeadRestartedError(method) from e
        # Idempotent reads survive transient connection hiccups (head busy,
        # socket reset during a head restart window) on the unified
        # deadline/backoff policy (core/deadline.py).  Timeouts are NOT
        # retried: a stuck head would just multiply the caller's wait; only
        # connection-level failures qualify.  When the connection is
        # genuinely DOWN (head restart window), retries — with reconnect
        # attempts between them — continue until the outage Deadline
        # (head_restart_retry_window_s) expires: the bounded pause a
        # head-routed read pays across a head restart.
        from . import deadline as _dl

        policy = _dl.call_policy()
        last: Optional[BaseException] = None
        attempt = 0
        outage_deadline: Optional[_dl.Deadline] = None
        while True:
            try:
                return self.rpc.call(method, body, timeout=timeout)
            except (ConnectionLost, ConnectionError, OSError) as e:
                if isinstance(e, TimeoutError):
                    raise
                last = e
                attempt += 1
                _dl.count_retry("head")
                closed = bool(getattr(self.rpc, "closed", False))
                if not closed and attempt >= get_config().rpc_retry_attempts:
                    raise last
                if closed:
                    if outage_deadline is None:
                        outage_deadline = _dl.Deadline.after(
                            get_config().head_restart_retry_window_s)
                    if outage_deadline.expired:
                        _dl.count_deadline_exceeded("head")
                        raise last
                policy.sleep(attempt, outage_deadline)
                if self.rpc.closed:
                    # A dead RpcClient never heals on its own (sticky
                    # `closed`): without a fresh connection the remaining
                    # attempts would fail identically.
                    try:
                        self._try_reconnect()
                    except Exception:
                        pass

    def _try_reconnect(self) -> bool:
        """Recovery from a lost head connection (e.g. a head restart
        window): dial a fresh RpcClient, re-register carrying the SAME
        identity, re-subscribe pubsub topics, and swap it in.  Drivers AND
        workers reconnect — a worker re-register is the field-state resync
        half of head fault tolerance (the restarted head adopts the live
        worker, its hosted actor, and its incarnation instead of treating
        the process as dead).  Proxy drivers don't: their mode/session
        state is negotiated in the initial register reply, and a silent
        re-register could flip the head's view of the protocol
        mid-stream."""
        if self.kind not in ("driver", "worker") or self.proxy:
            return False
        if self.reconnect_refused is not None:
            return False  # the head told us to stay dead; retrying is noise
        from . import schema as wire_schema

        # One reconnector at a time: concurrent retry paths (user thread +
        # autoscaler/serve poll threads) would each dial and register, and
        # the loser's swap would close the winner's fresh connection —
        # leaving a duplicate driver registration head-side whose
        # disconnect fires job-scoped cleanup against live state.
        with self._reconnect_lock:
            if not self.rpc.closed:
                return True  # another caller already healed the connection
            return self._reconnect_locked(wire_schema)

    def _reconnect_body(self, wire_schema) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            "kind": self.kind, "pid": os.getpid(),
            "protocol": wire_schema.PROTOCOL_VERSION,
            # Same-process re-dial: lets the head un-retire this pid's
            # cumulative metrics instead of double-counting them (and
            # never confuse a recycled pid for a comeback).
            "reconnect": True,
        }
        for key in ("worker_id", "node_id", "log_path", "peer_addr"):
            val = self._reg_info.get(key)
            if val:
                body[key] = val
        payload_fn = self.resync_payload
        if payload_fn is not None:
            try:
                resync = payload_fn()
            except Exception:
                resync = None
            if resync:
                body["resync"] = resync
        return body

    def _reconnect_locked(self, wire_schema) -> bool:
        rpc = None
        try:
            host, port = self.head_addr.rsplit(":", 1)
            rpc = RpcClient(host, int(port), name=f"{self.kind}-rpc")
            # The fresh connection inherits EVERY push handler (execute_task
            # / cancel / lease_revoke / pubsub / ...) BEFORE registering:
            # the head may push work the moment the register reply is sent.
            for name, fn in list(self.rpc._push_handlers.items()):
                rpc.on_push(name, fn)
            rpc.on_push("pubsub", self._on_pubsub)
            rpc.on_push("object_free", self._on_object_free)
            reply = rpc.call("register", self._reconnect_body(wire_schema))
            if reply.get("refused"):
                # The head explicitly refused to adopt this identity (stale
                # worker incarnation, dead actor): publish the reason so the
                # owner's reconnect loop exits instead of retrying forever.
                self.reconnect_refused = str(reply["refused"])
                rpc.close()
                return False
            if self.kind == "driver" and reply.get("session") != self.session:
                # A different session means a head restart LOST the store
                # namespace this driver's puts live in (no stable
                # RT_HEAD_SESSION): a silent rebind would look healthy until
                # the first object access hung.  Surface the outage instead.
                # (A standalone head restarted with the same session is
                # indistinguishable from a network blip here — by design.)
                rpc.close()
                return False
            with self._sub_lock:
                topics = list(self._sub_handlers)
            for topic in topics:
                rpc.call("subscribe", {"topic": topic})
            # The replacement inherits the lost-connection callback only
            # once registration succeeded — a drop during the handshake is
            # handled by this method's own failure path, not by spawning a
            # second reconnect loop.  (The old client's attribute still
            # holds the owner's callback: close() nulls it after the swap.)
            rpc.on_connection_lost = self.rpc.on_connection_lost
            self.trace_sample_rate = reply.get(
                "trace_sample_rate", self.trace_sample_rate)
        except Exception:
            if os.environ.get("RT_DEBUG_RPC_ERR"):
                import sys as _sys
                import traceback as _tb

                print("reconnect attempt failed:", file=_sys.stderr)
                _tb.print_exc()
            # A dial that got as far as registering left a live duplicate
            # driver connection head-side: close it so its disconnect
            # cleanup runs NOW (against a connection that owns nothing)
            # rather than minutes later against this driver's live state —
            # and so each failed attempt doesn't leak a socket + thread.
            if rpc is not None:
                try:
                    rpc.close()
                except Exception:
                    pass
            return False  # head still down: the caller's backoff continues
        old, self.rpc = self.rpc, rpc
        try:
            old.on_connection_lost = None  # its loss already happened
            old.close()  # stop the dead client's event-loop thread
        except Exception:
            pass
        # Field-state resync, client half: a restarted head's object
        # directory is rebuilt from live reports — re-register this
        # process's large shm puts so its refs stay resolvable.  Rides the
        # put batch (FIFO ahead of anything that references them); the
        # restarted head's adopt path tolerates already-known objects, so
        # a plain network blip just re-asserts existing records.
        with self._local_lock:
            large = list(self.large_oids.items())
        if large and self.node_id is not None:
            with self._put_batch_lock:
                self._put_batch[:0] = [
                    {"object_id": raw, "size": size,
                     "node_id": self.node_id.binary()}
                    for raw, size in large
                ]
        if self._dataplane is not None:
            try:
                # Held leases died with the old head: drop the slots (their
                # lease ids mean nothing to the new incarnation) and
                # re-route queued specs; cached direct-actor routes stay —
                # the workers survived and their peer servers kept serving.
                self._dataplane.on_head_reconnected()
            except Exception:
                pass
        # Replay everything buffered during the headless window (task_done
        # reports, submissions, object registrations).
        try:
            self._flush_put_batch()
            self._flush_submit_batch()
        except Exception:
            pass
        cb = self.on_reconnected
        if cb is not None:
            try:
                cb()
            except Exception:
                pass
        # The free-flusher thread exits when it observes a closed rpc; if it
        # died during the outage window, object frees (and the batched
        # put/submit safety-net flush) would silently stop forever.  The
        # brief join drains a loop that already decided to exit but hasn't
        # returned yet (its wakeup period is 0.5s).
        flusher = getattr(self, "_free_flusher", None)
        if flusher is not None and flusher is not threading.current_thread():
            flusher.join(timeout=1.0)
        if flusher is None or not flusher.is_alive():
            self._free_flusher = threading.Thread(
                target=self._free_flush_loop, daemon=True, name="free-flusher"
            )
            self._free_flusher.start()
        # Reads work again, but the OLD connection's death already tore
        # down job-scoped state head-side (non-detached placement groups,
        # in-flight task ownership).  Say so loudly instead of letting a
        # later hang be the first symptom.  (Workers skip the warning —
        # their reconnect is the designed headless-recovery path and the
        # head logs the resync.)
        if self.kind == "driver":
            import warnings

            warnings.warn(
                "ray_tpu driver reconnected to the head after a lost "
                "connection; job-scoped state tied to the old connection "
                "(non-detached placement groups, in-flight head-routed "
                "tasks) may have been released — resubmit anything that "
                "fails with HeadRestartedError",
                RuntimeWarning,
                stacklevel=3,
            )
        return True

    def close(self):
        try:
            # Final span flush: a short-lived driver's trailing spans must
            # not die in the ring (only for the session's active client —
            # a tooling client closing must not steal another's spans).
            from .context import ctx as _ctx

            if _ctx.client is self:
                from ..util import tracing as _tracing

                _tracing.flush_spans(self)
        except BaseException:  # noqa: BLE001 — shutdown is best-effort
            pass
        try:
            self.drain_bg(timeout=5.0)
        except BaseException:  # noqa: BLE001 — shutdown is best-effort
            pass
        if self._dataplane is not None:
            try:
                # Return held leases + close peer connections before the
                # head connection drops (disconnect would release them
                # anyway; this keeps shutdown deterministic).
                self._dataplane.close()
            except BaseException:  # noqa: BLE001
                pass
        for st in self._stores_snapshot():
            st.close()
        self.rpc.close()
