"""Central configuration flag table.

Equivalent in role to the reference's RAY_CONFIG macro table
(reference: src/ray/common/ray_config_def.h — 215 flags, overridable via
RAY_<flag> env vars): one declarative registry, env-var overridable with the
``RT_`` prefix, plus per-``init()`` overrides via ``system_config={...}``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict


#: Spawn-env contract: every ``RT_*`` environment variable the package
#: reads AD HOC (raw ``os.environ``, outside this module).  These are the
#: process-boundary half of the config surface — the head exports them
#: into node-daemon envs, node daemons into worker envs, operators into
#: CLI/driver envs — and a key that drifts on one side fails silently
#: (``environ.get`` defaults kick in).  rtlint RT009 reconciles this
#: catalog against the package's actual reads and spawn-site exports
#: (missing / stale / orphan-write, mirroring RT003's three-way shape).
#: ``Config`` fields need no entry: ``RT_<FIELD>`` overrides resolve
#: through ``_env_override`` below and ad-hoc reads of them are flagged.
SPAWN_ENV_CONTRACT = {
    # -- cluster topology (spawner -> child) ----------------------------------
    "RT_ADDRESS": "head address for attach paths: CLI, autoscaler, "
                  "job-submission drivers, Cluster.attach",
    "RT_HEAD_ADDR": "head address exported to spawned node daemons and "
                    "workers (their Client dials it at boot)",
    "RT_NODE_ID": "pre-assigned node id for spawned daemons/workers; also "
                  "read by train sessions for rank placement metadata",
    "RT_SESSION": "store session namespace a worker's object writes land "
                  "in (set by the node daemon / head spawner)",
    "RT_NODE_SESSION": "store session a spawned node daemon serves "
                       "(cluster_utils launches daemons with the cluster "
                       "session; unset daemons mint their own)",
    "RT_PEER_HOST": "host the worker's peer RPC server binds (defaults "
                    "to loopback; multi-host spawners set the node IP)",
    "RT_LOG_PATH": "log file the spawner redirected this process into; "
                   "registered in the head's cluster log index",
    "RT_NODE_RESOURCES": "JSON resource map for a spawned node daemon",
    "RT_NODE_LABELS": "JSON label map for a spawned node daemon",
    "RT_NODE_NUM_WORKERS": "worker-pool cap for a spawned node daemon",
    "RT_NODE_HOST": "bind host for a spawned node daemon's servers",
    "RT_DRAIN_GRACE_S": "SIGTERM drain grace window for node daemons "
                        "(cluster_utils preemption uses the same knob)",
    # -- driver/operator knobs ------------------------------------------------
    "RT_NUM_TPUS": "TPU resource count override for init()",
    "RT_TPU_ACCELERATOR_TYPE": "accelerator type override for init()",
    "RT_TPU_CHIPS": "chip-inventory override for accelerator detection",
    "RT_TPU_GCE_METADATA": "1 = allow GCE metadata-server TPU probes",
    "RT_PRESTART_WORKERS": "cap on workers pre-started at init()",
    "RT_LOG_TO_DRIVER": "0 = don't mirror worker stdout/stderr to the "
                        "driver via pubsub",
    "RT_FORCE_PROXY_DRIVER": "1 = force the off-host proxy driver path "
                             "(tests; hosts without usable /dev/shm)",
    # -- standalone head (core/head_main.py) ----------------------------------
    "RT_HEAD_PORT": "fixed listen port for a standalone head daemon — a "
                    "restarted head must rebind the SAME port so headless "
                    "nodes/workers/drivers can redial it",
    "RT_HEAD_SESSION": "stable session name for a standalone head — a "
                       "restart keeps the store namespace so pre-crash "
                       "segments stay addressable",
    # -- fault injection (util/netfault.py) -----------------------------------
    "RT_NETFAULT": "network fault schedule DSL; every process that opens "
                   "an RPC endpoint arms it (children inherit the env, so "
                   "one export perturbs the whole cluster)",
    "RT_NETFAULT_SEED": "integer seed making the armed schedule's fault "
                        "sequence replayable (chaos_soak.sh --netfault "
                        "rotates it and prints the failing value)",
    "RT_CHAOS_STRAGGLER": "gang straggler schedule DSL (util/chaos."
                          "StragglerSchedule): phase=data|compute|"
                          "checkpoint,ms=,ranks= — the seeded rank "
                          "sleeps ms in that phase each round; train "
                          "workers inherit it via the gang runtime_env",
    "RT_CHAOS_SEED": "integer seed for chaos victim selection — the "
                     "straggler schedule's rank pick and the kill-"
                     "cadence tests' RNGs (chaos_soak.sh rotates it)",
    # -- debug switches -------------------------------------------------------
    "RT_DEBUG_PUSH": "worker-side push/exec tracing to stderr",
    "RT_DEBUG_RPC_ERR": "server-side RPC handler error dumps to stderr",
    "RT_DEBUG_LOCKS": "lock sentinel level: 1 = ordering checks, 2 = + "
                      "guard-map race sentinel (devtools.locks)",
    "RT_DEBUG_LOCKS_HOLD_S": "long-hold warning threshold for the lock "
                             "sentinel",
    "RT_DEBUG_JIT": "recompile sentinel: after the engine/learner warmup "
                    "arms it, any post-warmup retrace of a registered jit "
                    "program raises RecompileError with the arg "
                    "shape/dtype delta (devtools.jitguard)",
    "RT_NATIVE_SANITIZE": "build the _native helper with a sanitizer",
}


def _env_override(name: str, default):
    raw = os.environ.get(f"RT_{name.upper()}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclasses.dataclass
class Config:
    # -- object store ---------------------------------------------------------
    # Objects smaller than this are inlined in RPC messages instead of going
    # through shared memory (analog of Ray's in-process memory store for small
    # objects, reference: src/ray/core_worker/store_provider/memory_store).
    inline_object_max_bytes: int = 100 * 1024
    # Per-process in-process store budget for small objects (the analog of
    # the reference's CoreWorkerMemoryStore): owned puts and read inline
    # values are cached here so repeated gets skip the control plane.
    local_store_max_bytes: int = 128 * 1024 * 1024
    # Total shared-memory budget per node before eviction/spilling kicks in.
    # 0 = auto: 30% of system RAM (the reference's default share), capped at
    # 32 GiB (resolved in __post_init__).
    object_store_memory: int = 0
    # Directory used for spilling objects under memory pressure
    # (reference: python/ray/_private/external_storage.py FileSystemStorage).
    spill_dir: str = "/tmp/ray_tpu_spill"
    # -- scheduler ------------------------------------------------------------
    # Hybrid policy: pack onto low-index nodes until utilization crosses this
    # threshold, then spread (reference:
    # src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # -- workers --------------------------------------------------------------
    num_workers: int = 0  # 0 => num_cpus
    # A spawned worker process that hasn't registered within this window is
    # presumed dead; its spawn slot is reclaimed so the pool can retry.
    worker_register_timeout_s: float = 30.0
    # Idle task-workers older than this are reaped by the head's periodic
    # loop (reference: worker_pool.h idle worker killing).
    idle_worker_killing_time_s: float = 300.0
    # Absolute ceiling on live workers per node, as a multiple of the pool
    # cap.  Blocked workers (parked in nested ray.get) each permit one extra
    # spawn so nested gets don't deadlock, but a deeply nested chain must not
    # fork unboundedly (reference: worker_pool.h maximum_startup_concurrency
    # bounds concurrent startup).
    worker_pool_hard_cap_multiple: int = 4
    # Fresh (never-used) idle workers to keep pre-forked per node: actor
    # creations grab one instantly instead of waiting out a fork+boot+
    # register cycle (reference: worker_pool.h prestart /
    # num_prestart_python_workers).  Opt-in (0 disables): on small hosts
    # the spare forks tax every init; production heads enable it via
    # system_config={"prestart_spare_workers": 2} or RT_PRESTART_SPARE_WORKERS.
    prestart_spare_workers: int = 0
    # -- memory pressure --------------------------------------------------------
    # Kill a worker when its node's host memory usage crosses this fraction
    # (reference: src/ray/common/memory_monitor.h:52 MemoryMonitor +
    # raylet/worker_killing_policy_group_by_owner.h).  Victims: retriable
    # leased tasks first, newest first; their tasks retry.  0 disables.
    memory_usage_threshold: float = 0.95
    # -- fault tolerance ------------------------------------------------------
    default_task_max_retries: int = 3
    # Finished task specs kept for object lineage reconstruction (their args
    # stay pinned while kept — the analog of the reference's lineage pinning,
    # reference_count.h:75).  0 disables reconstruction.
    lineage_max_entries: int = 10_000
    default_actor_max_restarts: int = 0
    # Liveness probing of worker/node processes whose TCP connection is still
    # open but whose event loop has wedged (reference:
    # gcs_health_check_manager.h).  Probes every period; declared dead after
    # `threshold` consecutive missed acks.  The 30s default window is
    # deliberately generous: a worker mid-way through one long GIL-holding
    # C call (huge unpickle, big numpy ufunc) can't ack from its rpc thread
    # and must not be shot for it.
    health_check_period_s: float = 5.0
    health_check_failure_threshold: int = 6
    # -- RPC ------------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_bytes: int = 512 * 1024 * 1024
    # Unified retry/backoff policy (core/deadline.py): EVERY retry loop —
    # idempotent head reads, node/worker reconnect, peer re-dials — backs
    # off on one jittered exponential curve built from these two knobs,
    # instead of per-call-site constants (reference:
    # src/ray/rpc/retryable_grpc_client.h shares one backoff across all
    # GCS calls).
    rpc_retry_base_s: float = 0.05
    rpc_retry_cap_s: float = 0.5
    rpc_retry_attempts: int = 3
    # -- dataplane (peer-to-peer calls + node-local task leases) --------------
    # Direct actor calls: after a head-mediated address resolution the
    # driver dials the owning worker's peer RPC server and submits actor
    # tasks peer-to-peer — the head sees liveness, restart events, and
    # batched telemetry, never per-call traffic (reference: core workers
    # submit actor tasks directly to each other, core_worker.proto
    # PushTask).  RT_DIRECT_CALLS=0 force-disables (every call falls back
    # to the head-mediated path).
    direct_calls: bool = True
    # Node-local task leasing: drivers lease execution slots (idle workers)
    # per resource shape from the head and submit stateless tasks straight
    # to the leased workers' peer servers (reference: raylet worker leasing,
    # node_manager.proto RequestWorkerLease).  RT_TASK_LEASES=0 disables.
    task_leases: bool = True
    # Leases are bounded: count per (client, shape) ...
    lease_max_slots: int = 8
    # ... and TTL (seconds).  Clients renew active leases in the background;
    # the head revokes unrenewed ones so a wedged client can't hold
    # capacity forever.
    lease_ttl_s: float = 10.0
    # Client-side: return a lease that served no task for this long, so
    # idle-held slots (and their reserved resources) flow back to the
    # cluster promptly.
    lease_idle_return_s: float = 2.0
    # Per-slot pipelining window: specs in flight on one leased worker
    # before the client queues locally.  Deep enough that a whole burst
    # ships in one coalesced flush (a shallow window dribbles the tail out
    # one send per completion, paying a loop wakeup each); bounded so a
    # runaway submit loop can't grow worker queues without limit.
    direct_inflight_per_slot: int = 256
    # Peer dials fail fast (a dead worker's address must not stall the
    # caller for the full control-plane connect timeout).
    peer_connect_timeout_s: float = 2.0
    # In-flight deadline budget for a direct peer call: a submitted call
    # that hasn't completed within this window is re-routed via the head
    # and its route quarantined — the gray-failure net that
    # peer_connect_timeout_s (dial only) cannot catch.  Generous by
    # default: a legitimately slow actor method must not trip it (the
    # worker-side dedup cache makes an early re-route harmless, but not
    # free).
    peer_call_deadline_s: float = 30.0
    # How long a quarantined peer route stays degraded to the head path
    # before the next dial re-probes it.
    peer_quarantine_probe_s: float = 5.0
    # Control-plane persistence: when set, the head snapshots its durable
    # state (KV table + named-actor specs) here and restores on startup —
    # the analog of GCS fault tolerance via Redis-backed tables
    # (reference: src/ray/gcs/store_client/redis_store_client.h:33).
    head_state_path: str = ""
    # -- head fault tolerance (headless degraded mode) ------------------------
    # How long a node daemon / worker keeps redialing a lost head before
    # giving up and self-terminating.  While headless, in-flight tasks,
    # direct actor calls, peer streaming, and granted leases keep running;
    # the deadline guarantees an orphaned cluster (head never restarted)
    # still dies instead of leaking forkserver/worker processes
    # (reference: GCS FT — raylets reconnect with a bounded
    # gcs_rpc_server_reconnect_timeout_s, ray_config_def.h).
    head_reconnect_deadline_s: float = 45.0
    # Client-side: how long idempotent head reads keep retrying (with
    # reconnect attempts between tries) across a head restart window
    # before surfacing the connection error — the "bounded pause" on
    # head-routed ops while the head is down.
    head_restart_retry_window_s: float = 20.0
    # Head-side: after a restart, how long the head waits for field-state
    # resync reports (workers re-registering with their live actors)
    # before replaying unclaimed named actors from the durable snapshot —
    # adopting a live actor must win over re-creating it fresh.  Also the
    # window during which submissions to not-yet-reported actors park
    # instead of failing.  Must comfortably exceed the reconnect loops'
    # max backoff (2 s), or adoptions lose the race to driver replays.
    head_resync_grace_s: float = 5.0
    # -- observability --------------------------------------------------------
    task_events_buffer_size: int = 100_000
    enable_timeline: bool = True
    # Root-trace sampling rate, handed to every registering process in the
    # register reply (the head is the config source, so ONE knob governs
    # the whole cluster): 1.0 traces every root span, 0 disables tracing;
    # tracing.trace(..., force=True) is the per-call override.
    trace_sample_rate: float = 1.0
    # Per-process bounded span ring (util/tracing.py): finished spans
    # buffer here and flush as one batched span_batch RPC on the
    # background-report cadence; overflow drops (counted in
    # ray_tpu_spans_dropped_total), never blocks the emitting thread.
    span_ring_size: int = 4096
    # Per-process bounded engine step-record ring (util/steprec.py): the
    # serve engine's flight recorder appends one fixed-size record per
    # decode step here; records flush as one batched engine_step_batch RPC
    # on the background-report cadence.  Overflow drops (counted in
    # ray_tpu_step_records_dropped_total), never blocks the decode loop.
    step_ring_size: int = 2048
    # Black-box sidecar: the last N step records are mirrored to a
    # *.steps.log file next to the worker's log on every flush, so a
    # SIGKILLed worker leaves its final steps on disk for
    # `ray_tpu logs --post-mortem`.  0 disables the sidecar.
    step_dump_records: int = 256
    # Minimum seconds between sidecar rewrites (the dump is a whole-file
    # rewrite of <= step_dump_records compact JSON lines).
    step_dump_interval_s: float = 1.0
    # Head-side retention: step records kept per engine for
    # list_state(kind="engine_steps") / `ray_tpu top`.
    engine_steps_max_records: int = 1024
    # Per-process bounded gang round-record ring (util/gangrec.py): the
    # train session appends one fixed-size record per training round
    # (step wall, data/collective/ack/checkpoint waits, tokens, MFU);
    # records flush as one batched gang_round_batch RPC on the
    # background-report cadence.  Overflow drops (counted in
    # ray_tpu_gang_rounds_dropped_total), never blocks report().
    gang_ring_size: int = 2048
    # Black-box sidecar: the last N round records are mirrored to a
    # *.rounds.log file next to the worker's log, so a SIGKILLed rank
    # leaves its final rounds on disk for `ray_tpu logs --post-mortem`.
    # 0 disables the sidecar.
    gang_dump_records: int = 256
    # Minimum seconds between rounds-sidecar rewrites.
    gang_dump_interval_s: float = 1.0
    # Head-side retention for the gang join: joined rounds kept per gang
    # for list_state(kind="gang_rounds") / `ray_tpu gang`, and the cap on
    # distinct gangs tracked at once (oldest-idle gang evicts first).
    gang_rounds_max_records: int = 512
    gang_rounds_max_gangs: int = 64
    # Per-process metrics flusher cadence (util/metrics.py).  An atexit hook
    # ships the final window regardless, so short-lived workers don't lose
    # their last deltas.
    metrics_flush_interval_s: float = 2.0
    # Head-side time-series retention: each (metric, tags) series keeps a
    # downsampled ring of this many samples, appended at most once per
    # min-interval (reference: the dashboard's time-series panels read the
    # GCS-aggregated OpenCensus views; here the head IS the store).
    metrics_history_max_samples: int = 360
    metrics_history_min_interval_s: float = 1.0
    # Ceiling on distinct retained series — a tag-cardinality explosion
    # must not grow head memory without bound; new series beyond the cap
    # are dropped (the ones already retained keep recording).
    metrics_history_max_series: int = 1024
    # -- health / incident plane (util/health.py, head wiring) ----------------
    # Master switch for the head's detector pass.  Detectors run on the
    # telemetry sampling cadence; the pass is O(watched series) and adds
    # no RPCs, so it stays on by default.
    health_enabled: bool = True
    # Suspicion window the counter-delta detectors (partition, drops,
    # stall pressure, head loop lag) evaluate over.
    health_window_s: float = 30.0
    # Hysteresis: an open incident whose detector stays quiet this long
    # flips to resolved (and stays in the bounded ring for `doctor`).
    health_resolve_after_s: float = 20.0
    # Bounded incident ring on the head (head-volatile, like the
    # timeline): oldest-resolved evict first.
    health_max_incidents: int = 256
    # SLO availability goal for the serve burn-rate detector: the error
    # budget is 1 - goal (0.95 -> 5% of requests may breach the latency
    # target before the budget burns).
    health_slo_goal: float = 0.95
    # Explicit TTFT/ITL targets (seconds) for the burn-rate detector.
    # 0 = take the targets serve deployments declare (autoscaling
    # target_ttft_s, published to the head at deploy); with neither, the
    # SLO detector stays silent — no target means no budget to burn.
    health_slo_ttft_s: float = 0.0
    health_slo_itl_s: float = 0.0
    # Multi-window burn evaluation spans (Google-SRE shape: BOTH windows
    # must burn above threshold for a firing).
    health_slo_fast_window_s: float = 60.0
    health_slo_slow_window_s: float = 300.0
    # Push-style alerting for incident open/resolve transitions:
    # "" disables, "log" writes WARNING lines to the head log, an
    # http(s):// URL gets a JSON POST per transition (fire-and-forget on
    # a daemon thread — a dead webhook never blocks the head loop).
    alert_sink: str = ""
    # -- debugging plane ------------------------------------------------------
    # Cluster-wide log index: every worker/daemon registers its log file at
    # startup; entries of exited processes are RETAINED for crash
    # post-mortems (`get_log` on a dead worker) until this bound evicts
    # them, dead-oldest first (reference: the GCS keeps worker table
    # entries past death for `ray logs`).
    log_index_max_entries: int = 2000
    # Per-task lifecycle histories (SUBMITTED/SCHEDULED/RUNNING/FINISHED/
    # FAILED transitions + failure traceback) retained for
    # list_state(kind="task_events") (reference: gcs_task_manager.h task
    # event store).  0 disables recording.
    task_history_max_tasks: int = 10_000
    # Transition events kept per task record (retry loops must not grow a
    # record without bound; the oldest post-SUBMITTED events drop first).
    task_history_max_events: int = 64

    def __post_init__(self):
        if self.object_store_memory == 0:
            try:
                ram = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            except (ValueError, OSError):
                ram = 8 * 1024**3
            self.object_store_memory = min(int(ram * 0.30), 32 * 1024**3)

    def apply_env_overrides(self) -> "Config":
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))
        self.__post_init__()
        return self

    def apply_overrides(self, overrides: Dict[str, Any] | None) -> "Config":
        for k, v in (overrides or {}).items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown system_config key: {k}")
            setattr(self, k, v)
        self.__post_init__()  # re-resolve auto (0) values
        return self


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg


def host_memory_used_frac() -> float:
    """This host's memory pressure from /proc/meminfo (the MemoryMonitor
    input — reference: src/ray/common/memory_monitor.h:52 reads the same
    kernel counters)."""
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                info[key] = int(rest.strip().split()[0])
        total = info["MemTotal"]
        avail = info.get("MemAvailable", total)
        return 1.0 - avail / total
    except (OSError, KeyError, ValueError, ZeroDivisionError):
        return 0.0
