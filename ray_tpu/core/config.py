"""Central configuration flag table.

Equivalent in role to the reference's RAY_CONFIG macro table
(reference: src/ray/common/ray_config_def.h — 215 flags, overridable via
RAY_<flag> env vars): one declarative registry, env-var overridable with the
``RT_`` prefix, plus per-``init()`` overrides via ``system_config={...}``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict


def _env_override(name: str, default):
    raw = os.environ.get(f"RT_{name.upper()}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclasses.dataclass
class Config:
    # -- object store ---------------------------------------------------------
    # Objects smaller than this are inlined in RPC messages instead of going
    # through shared memory (analog of Ray's in-process memory store for small
    # objects, reference: src/ray/core_worker/store_provider/memory_store).
    inline_object_max_bytes: int = 100 * 1024
    # Total shared-memory budget per node before eviction/spilling kicks in.
    object_store_memory: int = 2 * 1024**3
    # Directory used for spilling objects under memory pressure
    # (reference: python/ray/_private/external_storage.py FileSystemStorage).
    spill_dir: str = "/tmp/ray_tpu_spill"
    # -- scheduler ------------------------------------------------------------
    # Hybrid policy: pack onto low-index nodes until utilization crosses this
    # threshold, then spread (reference:
    # src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Cap on concurrent pending lease requests per scheduling class
    # (reference: normal_task_submitter.h max_pending_lease_requests).
    max_pending_leases_per_scheduling_class: int = 10
    # -- workers --------------------------------------------------------------
    num_workers: int = 0  # 0 => num_cpus
    worker_register_timeout_s: float = 30.0
    idle_worker_killing_time_s: float = 300.0
    # -- fault tolerance ------------------------------------------------------
    default_task_max_retries: int = 3
    default_actor_max_restarts: int = 0
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    # -- RPC ------------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_bytes: int = 512 * 1024 * 1024
    # -- observability --------------------------------------------------------
    task_events_buffer_size: int = 100_000
    enable_timeline: bool = True

    def apply_env_overrides(self) -> "Config":
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))
        return self

    def apply_overrides(self, overrides: Dict[str, Any] | None) -> "Config":
        for k, v in (overrides or {}).items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown system_config key: {k}")
            setattr(self, k, v)
        return self


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
