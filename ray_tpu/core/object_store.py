"""Shared-memory object store (plasma equivalent).

Role-equivalent to the reference's plasma store
(reference: src/ray/object_manager/plasma/store.h:55 PlasmaStore +
object_lifecycle_manager.h / eviction_policy.h): immutable sealed objects in
shared memory, zero-copy reads from any process on the node, LRU eviction with
spill-to-disk (reference: src/ray/raylet/local_object_manager.h:41 +
python/ray/_private/external_storage.py FileSystemStorage).

Implementation notes (TPU-first design):
- Each object is a file under /dev/shm mapped with mmap — no dependence on
  Python's multiprocessing resource tracker (which unlinks segments that other
  processes still map).  This mirrors plasma's fd-passing model with the unix
  permissions model doing the access control.
- Device arrays never live here: XLA owns TPU HBM.  The store holds host
  bytes; the TPU edge is `jax.device_put` at consumption time.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .ids import ObjectID
from ..devtools.locks import make_lock, make_rlock

_SHM_DIR = "/dev/shm"
_PREFIX = "rtpu"

# -- put-path contention accounting -------------------------------------------
# Stage attribution for the object-store write path (the committed baseline
# the zero-copy redesign must move — ROADMAP item 3): every large put's wall
# splits into serialize / alloc / first_touch / copy, plus the store-lock
# wait on the daemon's accounting lock.  Two sinks per observation: the
# cluster histograms (``ray_tpu_put_copy_seconds`` by stage,
# ``ray_tpu_store_lock_wait_seconds``) for `doctor --object-plane`, and a
# process-local accumulator bench_core/tests read without a cluster.

#: Cold segments below this size skip the pre-touch pass (the fault cost
#: of a few pages is noise; the Python per-page loop is not).
_PRETOUCH_MIN_BYTES = 1024 * 1024
_PAGE = mmap.PAGESIZE or 4096

_stage_lock = make_lock("store.put_stages")
_stage_acc: Dict[str, List[float]] = {}  # stage -> [seconds, bytes, count]
_stage_hist = None
_lock_hist = None

#: put-stage boundaries (seconds): large-put stages run 1ms..1s.
_STAGE_BOUNDS = (0.0005, 0.002, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0)


def note_put_stage(stage: str, seconds: float, nbytes: int = 0) -> None:
    """Attribute ``seconds`` of put wall to one named stage."""
    global _stage_hist
    if _stage_hist is None:
        from ..util.metrics import get_histogram

        _stage_hist = get_histogram(
            "ray_tpu_put_copy_seconds",
            "Object put wall time split by stage (serialize / alloc / "
            "first_touch / copy)", boundaries=_STAGE_BOUNDS,
            tag_keys=("stage",))
    _stage_hist.observe(seconds, {"stage": stage})
    with _stage_lock:
        acc = _stage_acc.get(stage)
        if acc is None:
            acc = _stage_acc[stage] = [0.0, 0.0, 0.0]
        acc[0] += seconds
        acc[1] += nbytes
        acc[2] += 1


def note_lock_wait(seconds: float) -> None:
    """Record one store-lock acquisition wait (daemon accounting lock)."""
    global _lock_hist
    if _lock_hist is None:
        from ..util.metrics import get_histogram

        _lock_hist = get_histogram(
            "ray_tpu_store_lock_wait_seconds",
            "Wait to acquire the object store's accounting lock",
            boundaries=(0.0001, 0.001, 0.005, 0.025, 0.1, 0.5, 1.0))
    _lock_hist.observe(seconds)
    with _stage_lock:
        acc = _stage_acc.get("lock_wait")
        if acc is None:
            acc = _stage_acc["lock_wait"] = [0.0, 0.0, 0.0]
        acc[0] += seconds
        acc[2] += 1


def put_stage_snapshot() -> Dict[str, dict]:
    """Process-local stage totals since start/reset (for bench + doctor)."""
    with _stage_lock:
        return {stage: {"seconds": acc[0], "bytes": int(acc[1]),
                        "count": int(acc[2])}
                for stage, acc in _stage_acc.items()}


def reset_put_stages() -> None:
    with _stage_lock:
        _stage_acc.clear()


def _pretouch(mm_buf, size: int) -> None:
    """Fault every page of a cold segment once (one byte store per page)
    so the copy that follows runs against warm pages — the fault cost
    becomes a measured ``first_touch`` stage instead of hiding inside the
    memcpy number.  Freshly created tmpfs segments read as zeros, and the
    stores write zeros, so content is unchanged."""
    for off in range(0, size, _PAGE):
        mm_buf[off] = 0


def _seg_path(session: str, object_id: ObjectID) -> str:
    return os.path.join(_SHM_DIR, f"{_PREFIX}-{session}-{object_id.hex()}")


def _pool_dir(session: str) -> str:
    return os.path.join(_SHM_DIR, f"{_PREFIX}-pool-{session}")


def _claim_pooled(session: str, path: str, size: int) -> Optional["_Segment"]:
    """Claim a warm segment from the session's free pool via atomic rename.

    tmpfs pages are expensive on first touch (allocate+zero page faults cap a
    cold 256 MiB write at well under 1 GiB/s on this class of machine) but
    nearly free on reuse, so freed segments are renamed into a pool instead
    of unlinked and new objects claim one of comparable size — the same
    reason the reference's plasma store allocates from a long-lived dlmalloc
    arena rather than mmap-per-object (reference:
    src/ray/object_manager/plasma/dlmalloc.cc)."""
    pool = _pool_dir(session)
    try:
        entries = os.listdir(pool)
    except FileNotFoundError:
        return None
    best = None
    best_delta = None
    for name in entries:
        try:
            fsize = int(name.split("-", 1)[0])
        except ValueError:
            continue
        # A smaller file still donates its warm prefix; a vastly larger one
        # wastes pooled bytes on ftruncate-down.  Prefer the closest size
        # within [size/2, 4*size].
        if fsize < size // 2 or fsize > 4 * size:
            continue
        delta = abs(fsize - size)
        if best_delta is None or delta < best_delta:
            best, best_delta = name, delta
    if best is None:
        return None
    try:
        os.rename(os.path.join(pool, best), path)
    except FileNotFoundError:
        return None  # lost the race to another writer
    try:
        seg = _Segment(path, size, create=False, exact_size=size)
    except OSError:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return None
    return seg


class _Segment:
    """A mapped shared-memory segment holding one sealed object."""

    __slots__ = ("path", "size", "mm", "fd")

    def __init__(self, path: str, size: int, create: bool,
                 exact_size: Optional[int] = None):
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        self.fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(self.fd, size)
            elif exact_size is not None:
                # Claimed from the warm pool: resize to the object's size
                # (shrinking keeps the warm prefix, growing adds cold tail).
                if os.fstat(self.fd).st_size != exact_size:
                    os.ftruncate(self.fd, exact_size)
                size = exact_size
            else:
                size = os.fstat(self.fd).st_size
            self.size = size
            self.mm = mmap.mmap(self.fd, size)
            self.path = path
        except Exception:
            os.close(self.fd)
            raise

    def view(self) -> memoryview:
        return memoryview(self.mm)

    def close(self) -> bool:
        """Returns True if the mapping was fully released; False when
        outstanding zero-copy views keep it alive (the caller must then treat
        the inode as still-read and never reuse it)."""
        clean = True
        try:
            self.mm.close()
        except (BufferError, ValueError):
            clean = False  # outstanding zero-copy views keep the map alive
        os.close(self.fd)
        return clean


class ObjectStore:
    """Node-scoped shared-memory object store with LRU eviction + spilling.

    One instance runs inside the node daemon (the accounting owner); worker
    and driver processes use :class:`StoreClient` views that attach segments
    read-only by name.
    """

    def __init__(self, session: str, capacity_bytes: int, spill_dir: str):
        self._session = session
        self._capacity = capacity_bytes
        self._spill_dir = os.path.join(spill_dir, session)
        os.makedirs(self._spill_dir, exist_ok=True)
        self._pool_dir = _pool_dir(session)
        os.makedirs(self._pool_dir, exist_ok=True)
        # Freed segments up to this many bytes stay pooled (pages warm) for
        # reuse by the next writer; beyond it they are unlinked.
        self._pool_cap = min(capacity_bytes // 2, 4 * 1024**3)
        self._lock = make_rlock("store.daemon")
        # Sealed objects in shm, LRU order (oldest first).
        self._objects: "OrderedDict[ObjectID, _Segment]" = OrderedDict()
        self._spilled: Dict[ObjectID, str] = {}
        self._pinned: Dict[ObjectID, int] = {}
        # Freed segments pass through here before entering the claimable
        # pool.  The owner's free is already gated on detach-acks from every
        # process that could hold a view (head._deferred_free), so no delay
        # is needed; the list only decouples pool bookkeeping from free().
        self._cooling: List[tuple] = []
        self._cooling_s = 0.0
        self._used = 0
        self.num_evictions = 0
        # Telemetry counters (cumulative; surfaced via stats() and the
        # head's ray_tpu_object_store_* built-in metrics).
        self.bytes_stored_total = 0
        self.bytes_transferred_total = 0
        self.gets_hit = 0
        self.gets_miss = 0

    # -- write path -----------------------------------------------------------

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        """Allocate a segment for an object; caller writes then calls seal()."""
        self.tick()
        _t_lk = time.perf_counter()
        with self._lock:
            note_lock_wait(time.perf_counter() - _t_lk)
            if object_id in self._objects:
                raise KeyError(f"object {object_id} already exists")
            self._ensure_capacity(size)
            path = _seg_path(self._session, object_id)
            _t0 = time.perf_counter()
            seg = _claim_pooled(self._session, path, size)
            if seg is None:
                seg = _Segment(path, size, create=True)
                note_put_stage("alloc", time.perf_counter() - _t0, size)
                if size >= _PRETOUCH_MIN_BYTES:
                    _t1 = time.perf_counter()
                    _pretouch(seg.mm, size)
                    note_put_stage("first_touch",
                                   time.perf_counter() - _t1, size)
            else:
                note_put_stage("alloc", time.perf_counter() - _t0, size)
            self._objects[object_id] = seg
            self._used += size
            self.bytes_stored_total += size
            return seg.view()

    def seal(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._objects[object_id].size

    def put_blob(self, object_id: ObjectID, blob: bytes) -> int:
        buf = self.create(object_id, len(blob))
        buf[:] = blob
        return self.seal(object_id)

    def adopt(self, object_id: ObjectID) -> int:
        """Take ownership (accounting + eviction) of a segment that a worker
        process created directly via StoreClient.create."""
        with self._lock:
            if object_id in self._objects:
                return self._objects[object_id].size
            seg = _Segment(_seg_path(self._session, object_id), 0, create=False)
            self._ensure_capacity(seg.size)
            self._objects[object_id] = seg
            self._used += seg.size
            self.bytes_stored_total += seg.size
            return seg.size

    # -- read path ------------------------------------------------------------

    def get(self, object_id: ObjectID) -> Optional[memoryview]:
        _t_lk = time.perf_counter()
        with self._lock:
            note_lock_wait(time.perf_counter() - _t_lk)
            seg = self._objects.get(object_id)
            if seg is not None:
                self._objects.move_to_end(object_id)  # LRU touch
                self.gets_hit += 1
                return seg.view()
            self.gets_miss += 1
            if object_id in self._spilled:
                return self._restore(object_id)
            return None

    def manifest(self) -> list:
        """(object_id, size) of every object this store can still serve —
        sealed shm segments plus spilled entries (restorable on access).
        The field-state report a node carries when it re-registers with a
        restarted head: the head rebuilds its volatile object directory
        from these (reference: GCS FT — raylets replay their object
        tables to a restarted GCS)."""
        out = []
        with self._lock:
            for oid, seg in self._objects.items():
                out.append((oid, seg.size))
            for oid, path in self._spilled.items():
                if oid in self._objects:
                    continue
                try:
                    out.append((oid, os.path.getsize(path)))
                except OSError:
                    pass  # spill file gone: nothing to report
        return out

    def count_transferred(self, nbytes: int) -> None:
        """Account bytes served to a cross-node pull (called by the pull
        handlers in node_main)."""
        with self._lock:
            self.bytes_transferred_total += nbytes

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects or object_id in self._spilled

    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    # -- lifecycle ------------------------------------------------------------

    def _pool_or_unlink(self, seg: _Segment):
        """Retire a freed segment: rename into the warm pool (keeping its
        pages for the next writer) while pooled bytes stay under the cap,
        else unlink.  Pooled bytes are recounted from the size-prefixed file
        names — writers consume pool entries without telling us."""
        pooled = 0
        try:
            for name in os.listdir(self._pool_dir):
                try:
                    pooled += int(name.split("-", 1)[0])
                except ValueError:
                    pass
        except FileNotFoundError:
            os.makedirs(self._pool_dir, exist_ok=True)
        if seg.size == 0 or pooled + seg.size > self._pool_cap:
            try:
                os.unlink(seg.path)
            except FileNotFoundError:
                pass
            return
        dst = os.path.join(
            self._pool_dir, f"{seg.size}-{os.urandom(4).hex()}"
        )
        try:
            os.rename(seg.path, dst)
        except FileNotFoundError:
            pass

    def tick(self):
        """Move cooled freed segments into the claimable pool.  Called from
        the owner's housekeeping loop and opportunistically from create()."""
        now = time.monotonic()
        with self._lock:
            while self._cooling and now - self._cooling[0][0] >= self._cooling_s:
                _, seg = self._cooling.pop(0)
                self._pool_or_unlink(seg)

    def free(self, object_id: ObjectID, pool: bool = True):
        """Release an object.  ``pool=False`` forces unlink (callers pass it
        when some process still holds zero-copy views of the segment — the
        orphaned inode then stays stable for those views, the pre-pool
        semantics; pooling would rewrite bytes under them)."""
        with self._lock:
            seg = self._objects.pop(object_id, None)
            if seg is not None:
                self._used -= seg.size
                if not seg.close():
                    pool = False  # our own mapping still has live views
                if object_id in self._pinned:
                    # An in-flight bulk transfer holds an fd (sendfile):
                    # unlink keeps the inode alive for that fd, pooling
                    # would let a new writer overwrite it mid-stream.
                    pool = False
                if pool:
                    self._cooling.append((time.monotonic(), seg))
                else:
                    try:
                        os.unlink(seg.path)
                    except FileNotFoundError:
                        pass
            spath = self._spilled.pop(object_id, None)
            if spath is not None:
                try:
                    os.unlink(spath)
                except FileNotFoundError:
                    pass
            self._pinned.pop(object_id, None)
        self.tick()

    def shutdown(self):
        with self._lock:
            for oid in list(self._objects):
                self.free(oid)
            for _, seg in self._cooling:
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
            self._cooling.clear()
            try:
                for name in os.listdir(self._pool_dir):
                    try:
                        os.unlink(os.path.join(self._pool_dir, name))
                    except FileNotFoundError:
                        pass
                os.rmdir(self._pool_dir)
            except OSError:
                pass

    # -- eviction / spilling --------------------------------------------------

    def _ensure_capacity(self, size: int):
        if size > self._capacity:
            raise MemoryError(
                f"object of {size} bytes exceeds store capacity {self._capacity}"
            )
        while self._used + size > self._capacity:
            victim = next(
                (oid for oid in self._objects if oid not in self._pinned), None
            )
            if victim is None:
                raise MemoryError(
                    f"object store full ({self._used} bytes, all pinned)"
                )
            self._spill(victim)

    def _spill(self, object_id: ObjectID):
        seg = self._objects.pop(object_id)
        path = os.path.join(self._spill_dir, object_id.hex())
        with open(path, "wb") as f:
            f.write(seg.view())
        self._spilled[object_id] = path
        self._used -= seg.size
        self.num_evictions += 1
        seg.close()
        try:
            os.unlink(seg.path)
        except FileNotFoundError:
            pass

    def _restore(self, object_id: ObjectID) -> memoryview:
        path = self._spilled.pop(object_id)
        with open(path, "rb") as f:
            blob = f.read()
        os.unlink(path)
        self._ensure_capacity(len(blob))
        seg = _Segment(_seg_path(self._session, object_id), len(blob), create=True)
        seg.view()[:] = blob
        self._objects[object_id] = seg
        self._used += len(blob)
        return seg.view()

    # -- stats ----------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "used_bytes": self._used,
                "capacity_bytes": self._capacity,
                "num_objects": len(self._objects),
                "num_spilled": len(self._spilled),
                "num_evictions": self.num_evictions,
                "bytes_stored_total": self.bytes_stored_total,
                "bytes_transferred_total": self.bytes_transferred_total,
                "gets_hit": self.gets_hit,
                "gets_miss": self.gets_miss,
            }


class StoreClient:
    """Read/write view of the node's store for worker & driver processes.

    Writers create segments directly (the daemon learns sizes via object
    registration in the control plane); readers attach by name.  Attached
    segments are cached so repeated gets are free.
    """

    def __init__(self, session: str):
        self._session = session
        self._attached: Dict[ObjectID, _Segment] = {}
        self._lock = make_lock("store.client_attach")

    def create(self, object_id: ObjectID, size: int,
               wait_pool_s: float = 0.0) -> memoryview:
        """Allocate a writable segment.  ``wait_pool_s`` bounds a brief wait
        for a warm pooled segment to appear — used when the caller knows
        frees are in flight (steady-state producers: reusing warm pages
        beats cold first-touch faults by ~10x under memory pressure)."""
        path = _seg_path(self._session, object_id)
        deadline = time.monotonic() + wait_pool_s
        _t0 = time.perf_counter()
        while True:
            seg = _claim_pooled(self._session, path, size)
            if seg is not None or time.monotonic() >= deadline:
                break
            time.sleep(0.003)
        if seg is None:
            seg = _Segment(path, size, create=True)
            note_put_stage("alloc", time.perf_counter() - _t0, size)
            if size >= _PRETOUCH_MIN_BYTES:
                _t1 = time.perf_counter()
                _pretouch(seg.mm, size)
                note_put_stage("first_touch", time.perf_counter() - _t1, size)
        else:
            # Pool claim (incl. any bounded wait for a warm segment): the
            # pages arrive warm, there is no first-touch stage to pay.
            note_put_stage("alloc", time.perf_counter() - _t0, size)
        with self._lock:
            self._attached[object_id] = seg
        return seg.view()

    def create_staged(self, object_id: ObjectID, size: int):
        """Create a segment at a temporary name; committing renames it to the
        object's canonical path atomically.  Used for inter-node pulls where
        several processes may fetch the same object concurrently — readers
        must never attach a partially-written segment (reference: plasma
        objects are invisible until sealed)."""
        final = _seg_path(self._session, object_id)
        tmp = f"{final}.pull-{os.getpid()}-{os.urandom(4).hex()}"
        seg = _claim_pooled(self._session, tmp, size)
        if seg is None:
            seg = _Segment(tmp, size, create=True)

        def commit() -> memoryview:
            os.rename(tmp, final)
            seg.path = final
            with self._lock:
                self._attached[object_id] = seg
            return seg.view()

        def abort():
            seg.close()
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass

        return seg.view(), commit, abort

    def get(self, object_id: ObjectID, timeout: float = 0.0) -> Optional[memoryview]:
        with self._lock:
            seg = self._attached.get(object_id)
            if seg is not None:
                return seg.view()
        deadline = time.monotonic() + timeout
        path = _seg_path(self._session, object_id)
        while True:
            try:
                seg = _Segment(path, 0, create=False)
                break
            except FileNotFoundError:
                if time.monotonic() >= deadline:
                    return None
                time.sleep(0.001)
        with self._lock:
            self._attached[object_id] = seg
        return seg.view()

    def detach(self, object_id: ObjectID) -> bool:
        """Unmap a segment.  Returns False when live zero-copy views (user
        code holding arrays aliasing the mmap) prevented the unmap — the
        store owner must then not recycle the inode."""
        with self._lock:
            seg = self._attached.pop(object_id, None)
        if seg is not None:
            return seg.close()
        return True

    def close(self):
        with self._lock:
            for seg in self._attached.values():
                seg.close()
            self._attached.clear()
