"""Message-framed RPC over asyncio TCP sockets.

Role-equivalent to the reference's gRPC layer (reference: src/ray/rpc/
grpc_server.h, client_call.h): request/response with per-call deadlines plus
server->client pushes (used for task dispatch and pubsub).  msgpack on the
wire; protobuf codegen isn't available in this image and the control-plane
messages are small, so a schema-light encoding is the right trade.

Frame format: [u32 length][msgpack payload]
Payload: [type, seq, method, body]  with type REQ=0 | RESP=1 | ERR=2 | PUSH=3.
"""

from __future__ import annotations

import asyncio
import struct
import threading
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ..devtools.locks import make_lock

REQ, RESP, ERR, PUSH = 0, 1, 2, 3
_HDR = struct.Struct("<I")

_max_msg_bytes: Optional[int] = None


def _msg_limit() -> int:
    global _max_msg_bytes
    if _max_msg_bytes is None:
        from .config import get_config

        _max_msg_bytes = get_config().rpc_max_message_bytes
    return _max_msg_bytes


def _encode(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    if len(body) > _msg_limit():
        raise RpcError(
            f"rpc message of {len(body)} bytes exceeds rpc_max_message_bytes "
            f"({_msg_limit()}); route bulk data through the object store"
        )
    return _HDR.pack(len(body)) + body


async def _read_msg(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_HDR.size)
    (n,) = _HDR.unpack(hdr)
    if n > _msg_limit():
        raise RpcError(f"incoming rpc frame of {n} bytes exceeds limit")
    body = await reader.readexactly(n)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class Connection:
    """One peer connection, server side."""

    _next_id = 0

    def __init__(self, reader, writer, server: "RpcServer"):
        self.reader = reader
        self.writer = writer
        self.server = server
        Connection._next_id += 1
        self.conn_id = Connection._next_id
        self.meta: Dict[str, Any] = {}
        self.alive = True
        self._write_lock = asyncio.Lock()

    async def push(self, method: str, body: Any):
        async with self._write_lock:
            self.writer.write(_encode([PUSH, 0, method, body]))
            await self.writer.drain()

    async def _send(self, msg):
        async with self._write_lock:
            self.writer.write(_encode(msg))
            await self.writer.drain()


class RpcServer:
    """Asyncio RPC server.  Handlers are ``async def handler(conn, body)`` or
    plain callables; return value becomes the response body."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.handlers: Dict[str, Callable[[Connection, Any], Awaitable[Any]]] = {}
        self.connections: Dict[int, Connection] = {}
        self.on_disconnect: Optional[Callable[[Connection], Awaitable[None]]] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def handler(self, name: str):
        def deco(fn):
            self.handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn):
        self.handlers[name] = fn

    # StreamReader buffer limit: the default 64 KiB forces a transport
    # pause/resume cycle every ~128 KiB, capping large-frame throughput at
    # ~0.2 GiB/s; object-plane chunks are 8 MiB.
    STREAM_LIMIT = 64 * 1024 * 1024

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port, limit=self.STREAM_LIMIT
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        # Close live connections first: in py3.12+ wait_closed() waits for all
        # connection handlers, which would deadlock while clients are attached.
        for conn in list(self.connections.values()):
            conn.writer.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self)
        self.connections[conn.conn_id] = conn
        try:
            while True:
                mtype, seq, method, body = await _read_msg(reader)
                if mtype == REQ:
                    asyncio.get_running_loop().create_task(
                        self._dispatch(conn, seq, method, body)
                    )
                # Servers ignore stray RESP/PUSH frames.
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            conn.alive = False
            self.connections.pop(conn.conn_id, None)
            writer.close()
            if self.on_disconnect is not None:
                await self.on_disconnect(conn)

    async def _dispatch(self, conn, seq, method, body):
        try:
            fn = self.handlers.get(method)
            if fn is None:
                raise RpcError(f"no handler for method {method!r}")
            result = fn(conn, body)
            if asyncio.iscoroutine(result):
                result = await result
            await conn._send([RESP, seq, method, result])
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            tb = traceback.format_exc()
            import os as _os
            if _os.environ.get("RT_DEBUG_RPC_ERR"):
                import sys as _sys
                print(f"RPC ERR in {method}: {e}\n{tb}", file=_sys.stderr,
                      flush=True)
            try:
                await conn._send([ERR, seq, method, f"{e}\n{tb}"])
            except Exception:
                pass


class RpcClient:
    """Thread-safe synchronous client over a background asyncio loop.

    Push handlers run on the loop; long handlers must hand off to a thread.
    """

    def __init__(self, host: str, port: int, name: str = "rpc-client"):
        self.host = host
        self.port = port
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=name, daemon=True
        )
        self._thread.start()
        self._seq = 0
        self._seq_lock = make_lock("rpc.seq")
        self._pending: Dict[int, asyncio.Future] = {}
        self._push_handlers: Dict[str, Callable[[Any], None]] = {}
        self._writer = None
        self._write_lock = None
        self._reader_task = None
        self.closed = False
        self.on_connection_lost: Optional[Callable[[], None]] = None
        from .config import get_config

        fut = asyncio.run_coroutine_threadsafe(self._connect(), self._loop)
        try:
            fut.result(timeout=get_config().rpc_connect_timeout_s)
        except BaseException:
            # A failed dial must not leak the loop thread started above:
            # callers that probe-and-retry (Cluster.attach fail-fast,
            # reconnect loops) would accumulate one live thread + event
            # loop per attempt.  close() is null-safe pre-connect.
            fut.cancel()
            self.close()
            raise

    async def _connect(self):
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=RpcServer.STREAM_LIMIT
        )
        self._write_lock = asyncio.Lock()
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    async def _read_loop(self):
        try:
            while True:
                mtype, seq, method, body = await _read_msg(self._reader)
                if mtype in (RESP, ERR):
                    fut = self._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        if mtype == RESP:
                            fut.set_result(body)
                        else:
                            fut.set_exception(RpcError(body))
                elif mtype == PUSH:
                    fn = self._push_handlers.get(method)
                    if fn is not None:
                        try:
                            fn(body)
                        except Exception:
                            traceback.print_exc()
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost("connection lost"))
            self._pending.clear()
            if self.on_connection_lost is not None:
                try:
                    self.on_connection_lost()
                except Exception:
                    traceback.print_exc()

    def on_push(self, method: str, handler: Callable[[Any], None]):
        self._push_handlers[method] = handler

    async def _send_request(self, seq, method, body):
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        async with self._write_lock:
            self._writer.write(_encode([REQ, seq, method, body]))
            await self._writer.drain()
        return await fut

    def call(self, method: str, body: Any = None, timeout: float = 60.0) -> Any:
        if self.closed:
            raise ConnectionLost("client is closed")
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        fut = asyncio.run_coroutine_threadsafe(
            self._send_request(seq, method, body), self._loop
        )
        return fut.result(timeout=timeout)

    def call_async(self, method: str, body: Any = None):
        """Fire a request, return a concurrent.futures.Future."""
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        return asyncio.run_coroutine_threadsafe(
            self._send_request(seq, method, body), self._loop
        )

    def close(self):
        if self.closed:
            return
        self.closed = True
        # Voluntary close: the lost-connection callback is for peer death,
        # not for our own teardown.
        self.on_connection_lost = None

        def _shutdown():
            async def _graceful():
                task = self._reader_task
                if task is not None:
                    task.cancel()
                    try:
                        # Let the cancellation unwind (its finally runs) so
                        # the loop doesn't destroy a pending task at stop.
                        await task
                    except BaseException:  # noqa: BLE001 — CancelledError
                        pass
                if self._writer is not None:
                    self._writer.close()
                self._loop.stop()

            asyncio.ensure_future(_graceful())

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=5)


class ServerThread:
    """Runs an RpcServer (plus arbitrary coroutines) on a dedicated thread."""

    def __init__(self, server: RpcServer):
        self.server = server
        self.loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True, name="rpc-server")

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_until_complete(self.server.start())
        self._started.set()
        self.loop.run_forever()

    def start(self) -> int:
        self.thread.start()
        self._started.wait(timeout=30)
        return self.server.port

    def run_coro(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _stop():
            await self.server.stop()
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_stop(), self.loop)
            self.thread.join(timeout=5)
        except Exception:
            pass
