"""Message-framed RPC over asyncio TCP sockets.

Role-equivalent to the reference's gRPC layer (reference: src/ray/rpc/
grpc_server.h, client_call.h): request/response with per-call deadlines plus
server->client pushes (used for task dispatch and pubsub).  msgpack on the
wire; protobuf codegen isn't available in this image and the control-plane
messages are small, so a schema-light encoding is the right trade.

Frame format: [u32 length][msgpack payload]
Payload: [type, seq, method, body]  with type REQ=0 | RESP=1 | ERR=2 | PUSH=3.
"""

from __future__ import annotations

import asyncio
import struct
import sys
import threading
import time
import traceback
from concurrent.futures import TimeoutError as _cf_TimeoutError
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ..devtools.locks import guarded, make_lock

REQ, RESP, ERR, PUSH = 0, 1, 2, 3
_HDR = struct.Struct("<I")

_max_msg_bytes: Optional[int] = None

# -- network fault injection (util/netfault.py) ------------------------------
# The armed FaultSchedule, or None.  Hot paths (per-frame send/receive)
# check this one global against None and touch nothing else — the injector
# hook is free when disabled.  Armed lazily from RT_NETFAULT at the first
# endpoint construction, or programmatically via set_fault_schedule.
_netfault = None
_netfault_env_checked = False

# Outbox queue-delay accounting (doctor --object-plane): how long requests
# sit in the coalescing outbox before the loop drains them — a congested
# shared loop (the peer dataplane multiplexes many connections over one)
# shows up here before it shows up anywhere else.  One observation per
# drained burst (the oldest entry's wait), armed lazily so client-less
# processes never build the instrument.
_outbox_hist = None


def _note_outbox_delay(seconds: float) -> None:
    global _outbox_hist
    if _outbox_hist is None:
        try:
            from ..util.metrics import get_histogram

            _outbox_hist = get_histogram(
                "ray_tpu_rpc_outbox_delay_seconds",
                "Request wait in the RPC outbox between enqueue and drain",
                boundaries=(0.0001, 0.001, 0.005, 0.025, 0.1, 0.5, 1.0))
        except Exception:
            return
    _outbox_hist.observe(seconds)


def _maybe_arm_netfault():
    global _netfault, _netfault_env_checked
    if _netfault_env_checked:
        return
    _netfault_env_checked = True
    import os

    spec = os.environ.get("RT_NETFAULT")
    if not spec:
        return
    try:
        from ..util.netfault import FaultSchedule

        _netfault = FaultSchedule(
            spec, int(os.environ.get("RT_NETFAULT_SEED", "0") or 0))
        print(f"netfault: armed seed={_netfault.seed} spec={spec!r}",
              file=sys.stderr, flush=True)
    except Exception as e:  # a bad spec must be loud, not a silent no-op
        print(f"netfault: failed to arm {spec!r}: {e}",
              file=sys.stderr, flush=True)


def set_fault_schedule(sched):
    """Install (or clear, with None) the process's fault schedule."""
    global _netfault, _netfault_env_checked
    _netfault = sched
    _netfault_env_checked = True


def _msg_limit() -> int:
    global _max_msg_bytes
    if _max_msg_bytes is None:
        from .config import get_config

        _max_msg_bytes = get_config().rpc_max_message_bytes
    return _max_msg_bytes


def _encode(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    if len(body) > _msg_limit():
        raise RpcError(
            f"rpc message of {len(body)} bytes exceeds rpc_max_message_bytes "
            f"({_msg_limit()}); route bulk data through the object store"
        )
    return _HDR.pack(len(body)) + body


async def _read_msg(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_HDR.size)
    (n,) = _HDR.unpack(hdr)
    if n > _msg_limit():
        raise RpcError(f"incoming rpc frame of {n} bytes exceeds limit")
    body = await reader.readexactly(n)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class Connection:
    """One peer connection, server side."""

    _next_id = 0

    def __init__(self, reader, writer, server: "RpcServer"):
        self.reader = reader
        self.writer = writer
        self.server = server
        Connection._next_id += 1
        self.conn_id = Connection._next_id
        self.meta: Dict[str, Any] = {}
        self.alive = True
        self._write_lock = asyncio.Lock()
        # Response/push coalescing: frames buffer here and ONE call_soon
        # flush per loop tick writes them all — a burst of completions
        # costs one send() syscall, not one per frame (send() is ~1ms on
        # sandboxed kernels and bounds per-connection message rate).
        self._outbuf = bytearray()
        self._flush_scheduled = False

    async def push(self, method: str, body: Any):
        await self._send([PUSH, 0, method, body])

    async def _send(self, msg):
        self._outbuf += _encode(msg)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self):
        self._flush_scheduled = False
        data, self._outbuf = bytes(self._outbuf), bytearray()
        if not data or not self.alive:
            return
        try:
            self.writer.write(data)
            # Coalesced writes skip drain() (its await would serialize the
            # burst) — so bound the transport buffer explicitly: a peer
            # that stopped reading must not grow server memory without
            # limit.  Closing trips the normal disconnect cleanup; the
            # health prober would have reaped such a peer anyway.
            if self.writer.transport.get_write_buffer_size() \
                    > RpcServer.STREAM_LIMIT:
                self.writer.close()
        except Exception:
            pass  # reader side notices the dead transport


class RpcServer:
    """Asyncio RPC server.  Handlers are ``async def handler(conn, body)`` or
    plain callables; return value becomes the response body."""

    _RT_UNGUARDED = {
        "handlers": "registered at server construction, before start() "
                    "opens the listening socket — no request can race the "
                    "registration",
    }

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "server"):
        _maybe_arm_netfault()
        self.host = host
        self.port = port
        self.name = name  # netfault link id (e.g. "peer-server")
        self.handlers: Dict[str, Callable[[Connection, Any], Awaitable[Any]]] = {}
        self.connections: Dict[int, Connection] = {}
        self.on_disconnect: Optional[Callable[[Connection], Awaitable[None]]] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def handler(self, name: str):
        def deco(fn):
            self.handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn):
        self.handlers[name] = fn

    # StreamReader buffer limit: the default 64 KiB forces a transport
    # pause/resume cycle every ~128 KiB, capping large-frame throughput at
    # ~0.2 GiB/s; object-plane chunks are 8 MiB.
    STREAM_LIMIT = 64 * 1024 * 1024

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port, limit=self.STREAM_LIMIT
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        # Close live connections first: in py3.12+ wait_closed() waits for all
        # connection handlers, which would deadlock while clients are attached.
        for conn in list(self.connections.values()):
            conn.writer.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self)
        self.connections[conn.conn_id] = conn
        try:
            if _netfault is not None:
                # Gray failure: the accept succeeded (the peer sees a live
                # TCP endpoint) but nothing is read — and therefore nothing
                # is ever answered — until the stall window closes.
                stall_s = _netfault.on_accept(self.name)
                if stall_s > 0:
                    await asyncio.sleep(stall_s)
            while True:
                mtype, seq, method, body = await _read_msg(reader)
                if mtype == REQ:
                    asyncio.get_running_loop().create_task(
                        self._dispatch(conn, seq, method, body)
                    )
                # Servers ignore stray RESP/PUSH frames.
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            conn.alive = False
            self.connections.pop(conn.conn_id, None)
            writer.close()
            if self.on_disconnect is not None:
                await self.on_disconnect(conn)

    async def _dispatch(self, conn, seq, method, body):
        try:
            fn = self.handlers.get(method)
            if fn is None:
                raise RpcError(f"no handler for method {method!r}")
            result = fn(conn, body)
            if asyncio.iscoroutine(result):
                result = await result
            await conn._send([RESP, seq, method, result])
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            tb = traceback.format_exc()
            import os as _os
            if _os.environ.get("RT_DEBUG_RPC_ERR"):
                import sys as _sys
                print(f"RPC ERR in {method}: {e}\n{tb}", file=_sys.stderr,
                      flush=True)
            try:
                await conn._send([ERR, seq, method, f"{e}\n{tb}"])
            except Exception:
                pass


@guarded
class RpcClient:
    """Thread-safe synchronous client over a background asyncio loop.

    Push handlers run on the loop; long handlers must hand off to a thread.
    """

    # rtlint RT007 verifies the outbox guards statically; RT_DEBUG_LOCKS=2
    # asserts them at runtime (devtools.locks).
    _RT_GUARDED_BY = {
        "_seq": "_seq_lock",
        "_outbox": "_seq_lock",
        "_outbox_scheduled": "_seq_lock",
    }
    _RT_UNGUARDED = {
        "closed": "monotonic bool flip: every writer stores True; readers "
                  "that lose the race fail into ConnectionLost anyway",
        "_push_handlers": "handlers are registered at client setup before "
                          "their method's pushes can arrive; dict get/set "
                          "are GIL-atomic",
        "on_connection_lost": "voluntary close() stores None so the "
                              "lost-connection callback is suppressed; a "
                              "racing read in the reader's teardown just "
                              "runs the old callback once, which close() "
                              "tolerates",
        "_pending": "seq-keyed entries: the loop thread sets and pops them; "
                    "call()'s timeout abandon pops only its OWN seq from "
                    "the caller thread (GIL-atomic dict.pop — one pop wins "
                    "and the loser's fut.done() check makes a double "
                    "resolve impossible); the dict itself is never rebound",
    }

    def __init__(self, host: str, port: int, name: str = "rpc-client",
                 connect_timeout_s: Optional[float] = None,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        _maybe_arm_netfault()
        self.host = host
        self.port = port
        self.name = name  # netfault rules link-match on this
        # ``loop``: run on a caller-owned shared loop instead of spawning a
        # thread per connection — the peer dataplane multiplexes many
        # worker connections over ONE loop thread (a reader thread per
        # connection thrashes small hosts).  close() leaves a shared loop
        # running.
        self._owns_loop = loop is None
        if loop is not None:
            self._loop = loop
            self._thread = None
        else:
            self._loop = asyncio.new_event_loop()
            self._thread = threading.Thread(
                target=self._loop.run_forever, name=name, daemon=True
            )
            self._thread.start()
        self._seq = 0
        self._seq_lock = make_lock("rpc.seq")
        self._pending: Dict[int, Any] = {}
        # Outbox coalescing: requests append here and at most ONE loop
        # wakeup is scheduled at a time.  call_soon_threadsafe costs a
        # self-pipe write syscall (~1ms on sandboxed kernels); a burst of N
        # submissions must pay it once, not N times.
        self._outbox: list = []
        self._outbox_scheduled = False
        self._push_handlers: Dict[str, Callable[[Any], None]] = {}
        self._writer = None
        self._write_lock = None
        self._reader_task = None
        self.closed = False
        self.on_connection_lost: Optional[Callable[[], None]] = None
        from .config import get_config

        fut = asyncio.run_coroutine_threadsafe(self._connect(), self._loop)
        try:
            # Peer-plane dials pass a short timeout: a dead worker's stale
            # address must fail fast into the head fallback, not stall the
            # caller for the full control-plane connect window.
            fut.result(timeout=connect_timeout_s
                       if connect_timeout_s is not None
                       else get_config().rpc_connect_timeout_s)
        except BaseException:
            # A failed dial must not leak the loop thread started above:
            # callers that probe-and-retry (Cluster.attach fail-fast,
            # reconnect loops) would accumulate one live thread + event
            # loop per attempt.  close() is null-safe pre-connect.
            fut.cancel()
            self.close()
            raise

    async def _connect(self):
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=RpcServer.STREAM_LIMIT
        )
        self._write_lock = asyncio.Lock()
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    def _handle_msg(self, mtype, seq, method, body):
        if mtype in (RESP, ERR):
            fut = self._pending.pop(seq, None)
            if fut is not None and not fut.done():
                if mtype == RESP:
                    fut.set_result(body)
                else:
                    fut.set_exception(RpcError(body))
        elif mtype == PUSH:
            fn = self._push_handlers.get(method)
            if fn is not None:
                try:
                    fn(body)
                except Exception:
                    traceback.print_exc()

    async def _read_loop(self):
        try:
            while True:
                mtype, seq, method, body = await _read_msg(self._reader)
                nf = _netfault
                if nf is not None:
                    act = nf.on_recv(self.name, method)
                    if act is not None:
                        if act["kind"] == "drop":
                            continue  # reply lost on the wire
                        if act["kind"] == "dup":
                            # Deliver twice: the second delivery exercises
                            # the abandoned-seq / double-resolve surface.
                            self._handle_msg(mtype, seq, method, body)
                self._handle_msg(mtype, seq, method, body)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError,
                asyncio.CancelledError):
            pass  # CancelledError: voluntary close() tearing the task down
        except BaseException as e:  # noqa: BLE001 — diagnose, treat as loss
            # An unexpected reader death (decode error, oversized frame) is
            # indistinguishable from connection loss to callers — but it is
            # a bug worth seeing: reconnect loops would redial forever.
            print(f"rpc {self.host}:{self.port} read loop died: "
                  f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
            traceback.print_exc()
        finally:
            self.closed = True
            self._fail_outbox()
            # list(): call()'s timeout abandon pops entries from a foreign
            # thread; iterate a snapshot, pop-racers are already resolved.
            for fut in list(self._pending.values()):
                if not fut.done():
                    fut.set_exception(ConnectionLost("connection lost"))
            self._pending.clear()
            if self.on_connection_lost is not None:
                try:
                    self.on_connection_lost()
                except Exception:
                    traceback.print_exc()

    def on_push(self, method: str, handler: Callable[[Any], None]):
        self._push_handlers[method] = handler

    def _fail_outbox(self):
        with self._seq_lock:
            stranded, self._outbox = self._outbox, []
            self._outbox_scheduled = False
        for _, _, _, fut, _ in stranded:
            if not fut.done():
                fut.set_exception(ConnectionLost("connection lost"))

    def _drain_outbox(self):
        """Loop thread: encode and write every queued request.  Loops until
        the outbox is observed empty with the scheduled flag still set, so
        a producer racing the drain never schedules a second wakeup — one
        self-pipe write per burst, however long the burst."""
        for _ in range(64):
            with self._seq_lock:
                batch, self._outbox = self._outbox, []
                if not batch:
                    self._outbox_scheduled = False
                    return
            # Outbox queue delay (doctor --object-plane): the oldest entry
            # in the batch waited longest between enqueue and drain — one
            # histogram observe per burst, not per request, keeps this off
            # the per-call cost.
            _note_outbox_delay(time.monotonic() - batch[0][4])
            data = bytearray()
            written: list = []
            nf = _netfault
            for seq, method, body, fut, _ in batch:
                if fut.done():
                    continue  # e.g. cancelled while queued
                try:
                    frame = _encode([REQ, seq, method, body])
                except Exception as e:  # oversized message etc.
                    fut.set_exception(e)
                    continue
                if nf is not None:
                    act = nf.on_send(self.name, method)
                    if act is not None:
                        if act["kind"] == "drop":
                            # Lost on the wire: the caller still awaits a
                            # reply that never comes, exactly like a real
                            # dropped packet — pending registered, frame
                            # never written.
                            self._pending[seq] = fut
                            continue
                        if act["kind"] == "delay":
                            self._pending[seq] = fut
                            self._loop.call_later(
                                act["delay_s"], self._write_late,
                                bytes(frame))
                            continue
                self._pending[seq] = fut
                written.append(seq)
                data += frame
            if not data:
                continue
            try:
                self._writer.write(bytes(data))
                # Same buffer bound as Connection._flush: a server that
                # stopped reading must not grow this process's memory
                # without limit — close, and the read loop's teardown
                # fails every pending future with ConnectionLost.
                if self._writer.transport.get_write_buffer_size() \
                        > RpcServer.STREAM_LIMIT:
                    self._writer.close()
            except Exception as e:
                for seq in written:
                    fut = self._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(ConnectionLost(str(e)))
        # Producer still ahead of us after many passes: yield one loop
        # iteration (reads must not starve) and keep the flag claimed.
        self._loop.call_soon(self._drain_outbox)

    def _write_late(self, frame: bytes):
        """Loop thread, via call_later: a netfault-delayed frame finally
        hits the wire (unless the connection died meanwhile)."""
        if self.closed or self._writer is None:
            return
        try:
            self._writer.write(frame)
        except Exception:
            pass  # read loop's teardown already failed the pending future

    def call(self, method: str, body: Any = None,
             timeout: Optional[float] = 60.0) -> Any:
        """Blocking request/reply.  ``timeout=None`` waits forever — the
        caller owns its own deadline (e.g. a ``get(timeout=-1)`` that is
        contractually infinite); prefer that over sentinel constants."""
        if self.closed:
            raise ConnectionLost("client is closed")
        fut = self.call_async(method, body)
        try:
            return fut.result(timeout=timeout)
        except _cf_TimeoutError:
            # Abandon the call: drop the pending entry so a late reply to
            # this seq is a silent no-op instead of a leaked future, and
            # cancel() so a queued-but-unsent request never hits the wire.
            self._pending.pop(getattr(fut, "_rt_seq", -1), None)
            fut.cancel()
            from .deadline import count_deadline_exceeded

            count_deadline_exceeded(self.name)
            raise

    def call_async(self, method: str, body: Any = None):
        """Fire a request, return a concurrent.futures.Future.  Requests
        coalesce through the outbox; ordering across call()/call_async()
        is the append order (single connection, FIFO)."""
        import concurrent.futures as _cf

        fut: _cf.Future = _cf.Future()
        if self.closed:
            fut.set_exception(ConnectionLost("client is closed"))
            return fut
        with self._seq_lock:
            self._seq += 1
            fut._rt_seq = self._seq  # call()'s timeout abandon keys on this
            self._outbox.append(
                (self._seq, method, body, fut, time.monotonic()))
            wake = not self._outbox_scheduled
            if wake:
                self._outbox_scheduled = True
        if wake:
            try:
                self._loop.call_soon_threadsafe(self._drain_outbox)
            except RuntimeError:  # loop already closed (shutdown race)
                self._fail_outbox()
        return fut

    def close(self):
        if self.closed:
            return
        self.closed = True
        # Voluntary close: the lost-connection callback is for peer death,
        # not for our own teardown.
        self.on_connection_lost = None

        def _shutdown():
            self._drain_outbox()  # flush straggler fire-and-forget requests

            async def _graceful():
                task = self._reader_task
                if task is not None:
                    task.cancel()
                    try:
                        # Let the cancellation unwind (its finally runs) so
                        # the loop doesn't destroy a pending task at stop.
                        await task
                    except BaseException:  # noqa: BLE001 — CancelledError
                        pass
                if self._writer is not None:
                    self._writer.close()
                if self._owns_loop:
                    self._loop.stop()

            asyncio.ensure_future(_graceful())

        try:
            self._loop.call_soon_threadsafe(_shutdown)
        except RuntimeError:
            return  # shared loop already stopped
        if self._owns_loop and self._thread is not None:
            self._thread.join(timeout=5)


class ServerThread:
    """Runs an RpcServer (plus arbitrary coroutines) on a dedicated thread."""

    def __init__(self, server: RpcServer):
        self.server = server
        self.loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True, name="rpc-server")

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_until_complete(self.server.start())
        self._started.set()
        self.loop.run_forever()

    def start(self) -> int:
        self.thread.start()
        self._started.wait(timeout=30)
        return self.server.port

    def run_coro(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _stop():
            await self.server.stop()
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_stop(), self.loop)
            self.thread.join(timeout=5)
        except Exception:
            pass
