"""Public API: init/shutdown, @remote tasks & actors, get/put/wait, placement
groups, named actors.

Role-equivalent to the reference's python/ray/_private/worker.py:1227 (init),
:2567/2693/2758 (get/put/wait), remote_function.py:40 (RemoteFunction),
actor.py:581 (ActorClass) / :1238 (ActorHandle), util/placement_group.py.
"""

from __future__ import annotations

import atexit
import hashlib
import inspect
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

import cloudpickle

from .. import exceptions
from . import serialization
from .client import Client
from .config import Config, get_config, set_config
from .context import ctx
from .head import Head
from .ids import ActorID, ObjectID, PlacementGroupID, TaskID
from .object_ref import ObjectRef, ObjectRefGenerator, _TopLevelRef
from .rpc import ServerThread
from .scheduler import SchedulingStrategy

_init_lock = threading.RLock()

# ----------------------------------------------------------------- scheduling


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[dict], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        # True when the bundles didn't fit the node set at creation time; the
        # PG stays pending until nodes join (callers on fixed clusters can
        # check this to fall back instead of blocking in ready()).
        self.infeasible_now = False

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until the group's bundles are reserved (False on timeout).
        Feasible-but-busy groups queue head-side until resources free up
        (reference: gcs_placement_group_manager pending queue)."""
        return ctx.client.call(
            "pg_ready",
            {"pg_id": self.id.binary(), "timeout": timeout},
            timeout=timeout + 30,
        )["ready"]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1):
        self.placement_group = placement_group
        self.bundle_index = placement_group_bundle_index

    def to_wire(self) -> dict:
        return {
            "kind": "placement_group",
            "pg_id": self.placement_group.id.binary(),
            "bundle_index": self.bundle_index,
        }


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def to_wire(self) -> dict:
        return {
            "kind": "node_affinity",
            "node_id": bytes.fromhex(self.node_id),
            "soft": self.soft,
        }


def _strategy_wire(strategy) -> Optional[dict]:
    if strategy is None or strategy == "DEFAULT":
        return None
    if strategy == "SPREAD":
        return {"kind": "spread"}
    if hasattr(strategy, "to_wire"):
        return strategy.to_wire()
    raise ValueError(f"unknown scheduling strategy {strategy!r}")


# ------------------------------------------------------------------- init


def _detect_resources(num_cpus=None, num_tpus=None, resources=None) -> Dict[str, float]:
    from ray_tpu import accelerators

    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is None:
        num_tpus = float(os.environ.get("RT_NUM_TPUS", 0))
    if num_tpus:
        out["TPU"] = float(num_tpus)
        # Pod-slice head marker resource, mirroring the reference's
        # TPU-v4-16-head style resources (python/ray/_private/accelerators/
        # tpu.py:198) so gang jobs can target a slice's head host.
        accel = os.environ.get("RT_TPU_ACCELERATOR_TYPE")
        if accel:
            out[f"TPU-{accel}-head"] = 1.0
    elif "TPU" not in out:
        # Autodetect chips from /dev (reference: tpu.py:97-117 counts
        # /dev/accel* at node start); explicit num_tpus/resources win.
        out.update(accelerators.node_resources())
    out.setdefault("memory", float(2**33))
    return out


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    num_workers: Optional[int] = None,
    namespace: str = "default",
    object_store_memory: Optional[int] = None,
    system_config: Optional[dict] = None,
    labels: Optional[Dict[str, str]] = None,
    ignore_reinit_error: bool = False,
    include_dashboard: bool = False,
    dashboard_port: int = 0,
):
    """Start (or connect to) a cluster.  With no address, an in-process control
    plane is started and worker processes are spawned on demand."""
    with _init_lock:
        if ctx.initialized:
            if ignore_reinit_error:
                return ctx
            raise RuntimeError("ray_tpu.init() called twice "
                               "(pass ignore_reinit_error=True to allow)")
        cfg = Config().apply_env_overrides().apply_overrides(system_config)
        if object_store_memory:
            cfg.object_store_memory = object_store_memory
        set_config(cfg)

        if address is None and os.environ.get("RT_ADDRESS"):
            address = os.environ["RT_ADDRESS"]

        if address is None:
            session = uuid.uuid4().hex[:12]
            head = Head(cfg, session)
            server_thread = ServerThread(head.server)
            # Head.start assigns the port inside the server thread's loop.
            server_thread.loop.call_soon_threadsafe(lambda: None)
            port = server_thread.start()
            head.port = port
            node_resources = _detect_resources(num_cpus, num_tpus, resources)
            cap = num_workers if num_workers is not None else (
                cfg.num_workers or int(node_resources["CPU"])
            )
            server_thread.run_coro(
                _add_local_node(head, node_resources, cap, labels)
            ).result(timeout=10)
            # Prestart the worker pool so first tasks don't pay process spawn
            # latency (reference: worker_pool.h prestarts num_cpus workers).
            prestart = min(cap, int(os.environ.get("RT_PRESTART_WORKERS", cap)))
            server_thread.run_coro(
                _prestart_workers(head, prestart)
            ).result(timeout=10)
            server_thread.run_coro(head.restore_state()).result(timeout=30)
            server_thread.run_coro(head.start_periodic()).result(timeout=10)
            ctx.head_process = (head, server_thread)
            address = f"127.0.0.1:{port}"
            os.environ["RT_ADDRESS"] = address
            # Discovery for out-of-process tooling (state CLI, job submit).
            try:
                os.makedirs("/tmp/ray_tpu", exist_ok=True)
                with open("/tmp/ray_tpu/latest_address", "w") as f:
                    f.write(address)
            except OSError:
                pass

        ctx.client = Client(address, kind="driver", pid=os.getpid())
        ctx.mode = "driver"
        ctx.session = ctx.client.session
        ctx.namespace = namespace
        if include_dashboard:
            from ray_tpu.dashboard import Dashboard

            ctx.dashboard = Dashboard(address, port=dashboard_port).start()
        if os.environ.get("RT_LOG_TO_DRIVER", "1") != "0":
            # Worker stdout/stderr arrive over pubsub (reference: the log
            # monitor republishes worker logs to the driver).
            def _print_worker_log(data):
                try:
                    prefix = f"(pid={data.get('pid')}) "
                    import sys as _sys

                    print(prefix + str(data.get("line", "")),
                          file=_sys.stderr
                          if data.get("stream") == "stderr" else _sys.stdout)
                except Exception:
                    pass

            try:
                ctx.client.subscribe("worker_logs", _print_worker_log)
            except Exception:
                pass
        atexit.register(shutdown)
        return ctx


async def _add_local_node(head: Head, resources, cap, labels):
    head.add_local_node(resources, cap, labels)


async def _prestart_workers(head: Head, n: int):
    for _ in range(n):
        head._spawn_worker(head.local_node_id)


def is_initialized() -> bool:
    return ctx.initialized


def _ensure_init():
    if not ctx.initialized:
        init()


def shutdown():
    with _init_lock:
        if not ctx.initialized:
            return
        head_proc = ctx.head_process
        client = ctx.client
        if ctx.dashboard is not None:
            try:
                ctx.dashboard.stop()
            except Exception:
                pass
        # Flush pending ObjectRef frees so a long-lived driver doesn't leave
        # up to a batch of shm segments behind.
        from .object_ref import _flush_free_queue

        try:
            _flush_free_queue()
        except Exception:
            pass
        try:
            if head_proc is not None:
                head, server_thread = head_proc
                try:
                    server_thread.run_coro(head.stop()).result(timeout=5)
                except Exception:
                    pass
                server_thread.stop()
            client.close()
        finally:
            os.environ.pop("RT_ADDRESS", None)
            ctx.reset()


# --------------------------------------------------------------- object API


def put(value: Any) -> ObjectRef:
    _ensure_init()
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return ObjectRef(ctx.client.put(value))


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: float = -1.0):
    _ensure_init()
    single = isinstance(refs, ObjectRef)
    batch = [refs] if single else list(refs)
    for r in batch:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    values = ctx.client.get(batch, timeout=timeout)
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
):
    _ensure_init()
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return ctx.client.wait(
        list(refs), num_returns, -1.0 if timeout is None else timeout
    )


def cancel(ref: ObjectRef, *, force: bool = False):
    _ensure_init()
    # Routed: direct-plane tasks cancel over the peer connection, head
    # tasks via the control plane.
    ctx.client.cancel_task(ref.task_id().binary(), force)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    _ensure_init()
    ctx.client.call(
        "kill_actor",
        {"actor_id": actor._actor_id.binary(), "no_restart": no_restart},
    )


# ----------------------------------------------------------------- functions


def _export(blob: bytes, prefix: str) -> str:
    """Export a pickled function/class to the cluster function table, dedup by
    content hash (reference: src/ray/gcs/gcs_server/gcs_function_manager.h).
    The export rides the background pipeline: the head processes it before
    any submission that references it (same connection, FIFO)."""
    key = f"{prefix}:{hashlib.sha1(blob).hexdigest()}"
    if key not in ctx.client.exported_keys:
        # First export of a key is synchronous so a failure (e.g. a blob over
        # the rpc size limit) raises here and is retried on the next call —
        # caching the key before a background send succeeded would suppress
        # re-export forever.  Amortized cost: one round trip per function.
        ctx.client.kv_put(key, blob, overwrite=False)
        ctx.client.exported_keys.add(key)
    return key


def _pack_args(args: tuple, kwargs: dict):
    """Replace top-level ObjectRefs with markers; returns (blob, arg_ids,
    args_ref).  Large argument payloads go to the object store."""
    cfg = get_config()
    arg_ids: List[bytes] = []
    proc_args = []
    for a in args:
        if isinstance(a, ObjectRef):
            arg_ids.append(a.binary())
            proc_args.append(_TopLevelRef(a.binary()))
        else:
            proc_args.append(a)
    proc_kwargs = {}
    for k, v in kwargs.items():
        if isinstance(v, ObjectRef):
            arg_ids.append(v.binary())
            proc_kwargs[k] = _TopLevelRef(v.binary())
        else:
            proc_kwargs[k] = v
    meta, buffers = serialization.serialize((tuple(proc_args), proc_kwargs))
    size = serialization.packed_size(meta, buffers)
    if size <= cfg.inline_object_max_bytes:
        blob = bytearray(size)
        serialization.pack_into(meta, buffers, memoryview(blob))
        return bytes(blob), arg_ids, None
    # Large args ride the object store instead of the RPC channel
    # (reference: _raylet.pyx submit_task puts large args into plasma).
    oid = ObjectID.from_random()
    buf = ctx.client.store().create(oid, size)
    serialization.pack_into(meta, buffers, buf)
    ctx.client.call(
        "put_object",
        {"object_id": oid.binary(), "size": size,
         "node_id": ctx.client.node_id.binary()},
    )
    return None, arg_ids, oid.binary()


def _package_working_dir(wd: str):
    """Zip a working_dir into a content-addressed (key, blob) pair."""
    import io
    import zipfile

    buf = io.BytesIO()
    n_files = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(wd):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for fname in files:
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, wd))
                n_files += 1
    if n_files == 0:
        raise ValueError(
            f"runtime_env working_dir {wd!r} is empty or does not exist"
        )
    blob = buf.getvalue()
    if len(blob) > 64 * 1024 * 1024:
        raise ValueError(
            f"working_dir archive is {len(blob)} bytes (>64MiB); ship large "
            "assets through the object store or shared storage instead"
        )
    key = f"wd:{hashlib.sha1(blob).hexdigest()}"
    return key, blob


def _process_runtime_env(renv: Optional[dict], cache: Optional[dict] = None):
    """Upload runtime_env payloads (content-addressed in the cluster KV) and
    rewrite the env to reference them.  `cache` memoizes the expensive zip
    across calls, but the kv upload is re-ensured per client so a
    shutdown()+init() cycle re-populates the new cluster's KV (reference:
    _private/runtime_env/working_dir.py URI-cached packages;
    runtime_env/py_modules.py ships import roots the same way)."""
    if not renv or not any(k in renv for k in
                           ("working_dir", "py_modules", "pip", "conda")):
        return renv
    cache = cache if cache is not None else {}
    out = dict(renv)

    def ensure(key, blob):
        if key not in ctx.client.exported_keys:
            ctx.client.kv_put(key, blob, overwrite=False)
            ctx.client.exported_keys.add(key)

    if "working_dir" in renv:
        if "key" in cache:
            key, blob = cache["key"], cache["blob"]
        else:
            key, blob = _package_working_dir(renv["working_dir"])
            cache["key"], cache["blob"] = key, blob
        ensure(key, blob)
        out.pop("working_dir")
        out["working_dir_key"] = key
    if "py_modules" in renv:
        # Each entry is a module DIRECTORY (or a module object); the worker
        # extracts it under an import root on sys.path (reference:
        # runtime_env/py_modules.py upload_py_modules_if_needed).
        mod_keys = cache.get("py_module_keys")
        if mod_keys is None:
            mod_keys = []
            for mod in renv["py_modules"]:
                path = getattr(mod, "__path__", None)
                if path is not None:
                    mod_dir = list(path)[0]
                elif isinstance(mod, str):
                    mod_dir = mod
                else:
                    raise TypeError(
                        "py_modules entries must be package directories "
                        f"(str) or package module objects, got {mod!r} "
                        "(single-file modules: ship their parent directory)"
                    )
                if not os.path.isdir(mod_dir):
                    raise ValueError(
                        f"py_modules entry {mod_dir!r} is not a directory"
                    )
                name = os.path.basename(mod_dir.rstrip("/"))
                if ":" in name:
                    raise ValueError(
                        f"py_modules directory name {name!r} may not "
                        "contain ':'"
                    )
                key, blob = _package_working_dir(mod_dir)
                key = f"pymod:{name}:{key.split(':', 1)[1]}"
                mod_keys.append((key, blob))
            cache["py_module_keys"] = mod_keys
        for key, blob in mod_keys:
            ensure(key, blob)
        out.pop("py_modules")
        out["py_module_keys"] = [k for k, _ in mod_keys]
    if "pip" in renv:
        # Per-task/actor python-dependency isolation (reference:
        # _private/runtime_env/pip.py + uri_cache.py): the env key is a
        # content hash of the requirement list (+ interpreter version);
        # local wheel/sdist files upload to the cluster KV so any node can
        # build the env without a shared filesystem or an index.
        pip_env = cache.get("pip_env")
        if pip_env is None:
            reqs = renv["pip"]
            if isinstance(reqs, dict):
                reqs = reqs.get("packages", [])
            if isinstance(reqs, str):
                with open(reqs) as f:
                    reqs = [ln.strip() for ln in f
                            if ln.strip()
                            and not ln.strip().startswith("#")]
            if not isinstance(reqs, (list, tuple)):
                raise TypeError("runtime_env['pip'] must be a list of "
                                "requirements, a requirements file path, "
                                "or {'packages': [...]}")
            normalized: List = []
            wheels: List = []
            for r in reqs:
                if isinstance(r, str) and os.path.isfile(r) and \
                        r.endswith((".whl", ".tar.gz", ".zip")):
                    with open(r, "rb") as f:
                        blob = f.read()
                    digest = hashlib.sha256(blob).hexdigest()[:16]
                    base = os.path.basename(r)
                    wheels.append((f"pipwhl:{digest}:{base}", blob, base))
                    normalized.append(("file", base, digest))
                else:
                    normalized.append(("req", str(r)))
            import sys as _sys

            env_hash = hashlib.sha256(repr(
                (normalized, _sys.version_info[:2])
            ).encode()).hexdigest()[:16]
            pip_env = cache["pip_env"] = {
                "hash": env_hash,
                "reqs": [list(n) for n in normalized],
                "wheel_keys": [(k, base) for k, _, base in wheels],
                "_wheel_blobs": wheels,
            }
        for key, blob, _ in pip_env["_wheel_blobs"]:
            ensure(key, blob)
        out.pop("pip")
        out["pip_env"] = {k: v for k, v in pip_env.items()
                          if k != "_wheel_blobs"}
    if "conda" in renv:
        # Conda envs (reference: _private/runtime_env/conda.py:260 —
        # content-addressed env creation from an environment dict, or
        # activation of a pre-existing named env).  The worker shells out
        # to the `conda` executable; clusters without conda fail fast
        # with a clear error at task setup.
        spec = renv["conda"]
        if isinstance(spec, dict):
            import json as _json

            canon = _json.dumps(spec, sort_keys=True)
            env_hash = hashlib.sha256(canon.encode()).hexdigest()[:16]
            conda_env = {"hash": env_hash, "spec": canon}
        elif isinstance(spec, str):
            # A named env / prefix path that must already exist.
            conda_env = {"name": spec}
        else:
            raise TypeError("runtime_env['conda'] must be an environment "
                            "dict or an existing env name/prefix")
        out.pop("conda")
        out["conda_env"] = conda_env
    return out


_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "retry_exceptions", "name", "scheduling_strategy", "runtime_env",
    "max_restarts", "max_task_retries", "max_concurrency", "lifetime",
    "namespace", "memory", "_metadata",
    "concurrency_groups", "execute_out_of_order", "concurrency_group",
}


def method(**method_options):
    """Annotate an actor method (reference: ray.method — actor.py:116
    ActorMethod decorator).  Supported: ``concurrency_group=`` binds the
    method to a named group declared in
    ``@remote(concurrency_groups={...})`` (reference:
    core_worker/transport/concurrency_group_manager.h); ``num_returns=``."""
    allowed = {"concurrency_group", "num_returns"}
    bad = set(method_options) - allowed
    if bad:
        raise ValueError(f"invalid method options: {bad}")

    def decorator(fn):
        fn.__rt_method_options__ = method_options
        return fn

    return decorator


def _inject_trace(spec: dict) -> None:
    """Propagate the active trace context into an outgoing task spec
    (reference: tracing_helper.py _DictPropagator injects the OTel span
    context into the spec's serialized runtime context).  The pre-assigned
    task_span_id makes the execution span's identity stable across retries.

    Each traced submission also records a zero-length *submit span* whose
    ``attrs.flow_id`` is the execution span's pre-assigned id:
    tracing.chrome_trace turns the pair into a flow arrow, so the timeline
    shows the scheduling gap between submit and execute."""
    import time as _time

    from ray_tpu.util import tracing

    parent = tracing.context_for_submit()
    if parent is not None:
        task_span_id = tracing.new_id()
        spec["trace_ctx"] = {
            "trace_id": parent["trace_id"],
            "span_id": parent["span_id"],
            "task_span_id": task_span_id,
        }
        now = _time.time()
        tracing.emit_span(tracing.make_span(
            parent, f"submit:{spec.get('name', 'task')}", now, now,
            flow_id=task_span_id))


def _resources_from_options(o: dict, default_cpu: float = 1.0) -> Dict[str, float]:
    res = dict(o.get("resources") or {})
    res["CPU"] = float(o["num_cpus"]) if o.get("num_cpus") is not None else default_cpu
    if o.get("num_tpus"):
        res["TPU"] = float(o["num_tpus"])
    if res.get("TPU"):
        # Whole-chip requests must map to a valid sub-host topology
        # (reference: tpu.py:141 validate_resource_request_quantity).
        from ray_tpu import accelerators

        err = accelerators.validate_request(res["TPU"])
        if err is not None:
            raise ValueError(err)
    if o.get("memory"):
        res["memory"] = float(o["memory"])
    return {k: v for k, v in res.items() if v}


class RemoteFunction:
    def __init__(self, fn, options: dict):
        self._fn = fn
        self._options = options
        self._exported_key: Optional[str] = None
        self._fn_blob: Optional[bytes] = None
        self._renv_cache: Optional[dict] = None  # processed runtime_env
        self.__name__ = getattr(fn, "__name__", "anonymous")

    def options(self, **overrides):
        bad = set(overrides) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"invalid options: {bad}")
        merged = {**self._options, **overrides}
        rf = RemoteFunction(self._fn, merged)
        rf._fn_blob = self._fn_blob
        return rf

    def _renv(self):
        # Options are immutable per instance: package the working_dir once,
        # not once per .remote(); the KV upload re-ensures per cluster.
        if self._renv_cache is None:
            self._renv_cache = {}
        return _process_runtime_env(
            self._options.get("runtime_env"), self._renv_cache
        )

    def remote(self, *args, **kwargs):
        _ensure_init()
        if self._fn_blob is None:
            self._fn_blob = cloudpickle.dumps(self._fn)
        key = _export(self._fn_blob, "fn")
        o = self._options
        task_id = TaskID.from_random()
        num_returns = o.get("num_returns", 1)
        streaming = num_returns == "streaming" or num_returns == "dynamic"
        n_ret = 1 if streaming else num_returns
        return_ids = [
            ObjectID.for_task_return(task_id, i) for i in range(n_ret)
        ]
        args_blob, arg_ids, args_ref = _pack_args(args, kwargs)
        cfg = get_config()
        spec = {
            "task_id": task_id.binary(),
            "name": o.get("name") or self.__name__,
            "func_key": key,
            "args": args_blob,
            "args_ref": args_ref,
            "arg_ids": arg_ids,
            "num_returns": "streaming" if streaming else num_returns,
            "return_ids": [r.binary() for r in return_ids],
            "resources": _resources_from_options(o),
            "strategy": _strategy_wire(o.get("scheduling_strategy")),
            "max_retries": o.get("max_retries", cfg.default_task_max_retries),
            "retry_exceptions": bool(o.get("retry_exceptions", False)),
            "runtime_env": self._renv(),
        }
        _inject_trace(spec)
        # Submission is pipelined AND batched — and, when a task lease is
        # held, routed straight to a leased worker's peer server with no
        # head traffic at all (reference: task submission is async; errors
        # surface on ray.get of the returned ref).
        ctx.client.submit_task(spec)
        if streaming:
            return ObjectRefGenerator(task_id.binary())
        refs = [ObjectRef(r) for r in return_ids]
        return refs[0] if n_ret == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use {self.__name__}.remote()."
        )


# -------------------------------------------------------------------- actors


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name
        self._options: dict = {}

    def options(self, **overrides):
        m = ActorMethod(self._handle, self._name)
        m._options = {**self._options, **overrides}
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit(self._name, args, kwargs, self._options)

    def bind(self, *upstreams):
        """Wire this method as a compiled-DAG step; multiple upstream nodes
        become the method's positional args (reference: dag/dag_node.py
        bind)."""
        from ..dag.compiled import bind as _dag_bind

        return _dag_bind(self, *upstreams)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_names: List[str],
                 max_task_retries: int = 0, class_name: str = "",
                 method_defaults: Optional[dict] = None):
        self._actor_id = actor_id
        self._method_names = method_names
        self._max_task_retries = max_task_retries
        self._class_name = class_name
        # Per-method option defaults from @ray_tpu.method annotations
        # (num_returns today); call-time .options() overrides them.
        self._method_defaults = method_defaults or {}

    def __getattr__(self, name):
        if name.startswith("_") and name != "__rt_dag_exec_loop__":
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}"
            )
        return ActorMethod(self, name)

    def _submit(self, method_name: str, args, kwargs, options: dict):
        _ensure_init()
        task_id = TaskID.from_random()
        defaults = self._method_defaults.get(method_name, {})
        num_returns = options.get(
            "num_returns", defaults.get("num_returns", 1))
        streaming = num_returns == "streaming"
        n_ret = 1 if streaming else num_returns
        return_ids = [ObjectID.for_task_return(task_id, i) for i in range(n_ret)]
        args_blob, arg_ids, args_ref = _pack_args(args, kwargs)
        spec = {
            "task_id": task_id.binary(),
            "actor_id": self._actor_id.binary(),
            "method_name": method_name,
            "name": f"{self._class_name}.{method_name}",
            "args": args_blob,
            "args_ref": args_ref,
            "arg_ids": arg_ids,
            "num_returns": "streaming" if streaming else num_returns,
            "return_ids": [r.binary() for r in return_ids],
            "max_retries": self._max_task_retries,
        }
        if options.get("concurrency_group") is not None:
            # Per-call group override (reference:
            # actor.py ActorMethod.options(concurrency_group=...)).
            spec["concurrency_group"] = options["concurrency_group"]
        _inject_trace(spec)
        # Peer-direct once the actor's address is resolved (the head sees
        # only liveness/telemetry, not per-call traffic); head-mediated
        # before that and on any peer-plane failure.
        ctx.client.submit_actor_task(spec)
        if streaming:
            return ObjectRefGenerator(task_id.binary())
        refs = [ObjectRef(r) for r in return_ids]
        return refs[0] if n_ret == 1 else refs

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._method_names, self._max_task_retries,
             self._class_name, self._method_defaults),
        )

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = options
        self._cls_blob: Optional[bytes] = None
        self._renv_cache: Optional[dict] = None  # processed runtime_env
        self.__name__ = cls.__name__

    def options(self, **overrides):
        bad = set(overrides) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"invalid options: {bad}")
        ac = ActorClass(self._cls, {**self._options, **overrides})
        ac._cls_blob = self._cls_blob
        return ac

    def _renv(self):
        if self._renv_cache is None:
            self._renv_cache = {}
        return _process_runtime_env(
            self._options.get("runtime_env"), self._renv_cache
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        _ensure_init()
        if self._cls_blob is None:
            self._cls_blob = cloudpickle.dumps(self._cls)
        key = _export(self._cls_blob, "cls")
        o = self._options
        cfg = get_config()
        actor_id = ActorID.from_random()
        task_id = TaskID.from_random()
        args_blob, arg_ids, args_ref = _pack_args(args, kwargs)
        method_names = [
            n for n, _ in inspect.getmembers(self._cls, callable)
            if not n.startswith("__") or n == "__rt_dag_exec_loop__"
        ]
        creation_task = {
            "task_id": task_id.binary(),
            "name": f"{self.__name__}.__init__",
            "func_key": key,
            "args": args_blob,
            "args_ref": args_ref,
            "arg_ids": arg_ids,
            "num_returns": 1,
            "return_ids": [ObjectID.for_task_return(task_id, 0).binary()],
            # Actors reserve no CPU by default (matching the reference:
            # actors get a dedicated worker process, not a CPU slot).
            "resources": _resources_from_options(o, default_cpu=0.0),
            "strategy": _strategy_wire(o.get("scheduling_strategy")),
            "max_retries": 0,
            "is_actor_creation": True,
            "actor_id": actor_id.binary(),
            "max_concurrency": o.get("max_concurrency", 1),
            "runtime_env": self._renv(),
        }
        groups = o.get("concurrency_groups")
        # Scan @ray_tpu.method annotations regardless of class options so a
        # group annotation without a declared group errors loudly instead
        # of silently losing its isolation (matching the reference).
        method_groups: Dict[str, str] = {}
        method_defaults: Dict[str, dict] = {}
        for n in method_names:
            fn = getattr(self._cls, n, None)
            opts = getattr(fn, "__rt_method_options__", None) \
                if fn is not None else None
            if not opts:
                continue
            g = opts.get("concurrency_group")
            if g is not None:
                if not groups or g not in groups:
                    raise ValueError(
                        f"method {n!r} declares concurrency group {g!r} "
                        "but the class does not declare it in "
                        "@remote(concurrency_groups={...})")
                method_groups[n] = g
            if opts.get("num_returns") is not None:
                method_defaults[n] = {"num_returns": opts["num_returns"]}
        if groups:
            # Named concurrency groups: per-group execution limits
            # (reference: concurrency_group_manager.h).
            if not all(isinstance(v, int) and v >= 1
                       for v in groups.values()):
                raise ValueError(
                    "concurrency_groups values must be ints >= 1")
            creation_task["concurrency_groups"] = dict(groups)
            creation_task["method_groups"] = method_groups
        if o.get("execute_out_of_order"):
            # Opt-in unordered DISPATCH: dependency-ready tasks may run
            # before earlier-submitted tasks still waiting on arguments, so
            # completion (and effect) order may differ from submission
            # order.  Execution concurrency is still bounded by
            # max_concurrency (reference: out_of_order_actor_submit_queue.h
            # reorders the submit queue without widening the pool).
            creation_task["execute_out_of_order"] = True
        spec = {
            "actor_id": actor_id.binary(),
            "class_name": self.__name__,
            "name": o.get("name"),
            "namespace": o.get("namespace", ctx.namespace),
            "max_restarts": o.get("max_restarts", cfg.default_actor_max_restarts),
            "max_task_retries": o.get("max_task_retries", 0),
            "method_names": method_names,
            "method_defaults": method_defaults,
            "lifetime": o.get("lifetime"),
            "creation_task": creation_task,
        }
        # Constructor args may be locally-cached direct-call results: the
        # head must know them before it dep-tracks the creation task.
        ctx.client.ensure_args_shared(creation_task)
        ctx.client.call("create_actor", spec)
        # Pre-warm the direct route: the ALIVE broadcast carries the
        # hosting worker's peer address and the client dials during
        # creation dispatch, not on the first call.
        ctx.client.prepare_actor_route(actor_id.binary())
        return ActorHandle(
            actor_id, method_names, spec["max_task_retries"], self.__name__,
            method_defaults,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    _ensure_init()
    reply = ctx.client.call("get_actor_by_name", {"name": name})
    if not reply["found"]:
        tomb = reply.get("tombstone")
        if tomb:
            raise ValueError(f"actor {name!r}: {tomb}")
        raise ValueError(f"no actor with name {name!r}")
    spec = reply["spec"]
    return ActorHandle(
        ActorID(reply["actor_id"]),
        spec["method_names"],
        spec.get("max_task_retries", 0),
        spec.get("class_name", ""),
        spec.get("method_defaults"),
    )


def list_named_actors() -> List[str]:
    _ensure_init()
    return ctx.client.call("list_named_actors")["names"]


# ------------------------------------------------------------------ decorator


def remote(*args, **options):
    """@remote decorator for functions and classes."""
    bad = set(options) - _VALID_OPTIONS
    if bad:
        raise ValueError(f"invalid @remote options: {bad}")

    def wrap(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and callable(args[0]) and not options:
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return wrap


# ------------------------------------------------------------ placement group


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    """Reserve resource bundles (reference: util/placement_group.py).

    ``lifetime="detached"`` decouples the group from its creator: it
    survives driver disconnect AND head restarts (persisted in the head
    snapshot, like detached named actors)."""
    _ensure_init()
    if lifetime not in (None, "detached"):
        raise ValueError("lifetime must be None or 'detached'")
    pg_id = PlacementGroupID.from_random()
    reply = ctx.client.call(
        "create_placement_group",
        {
            "pg_id": pg_id.binary(),
            "bundles": bundles,
            "strategy": strategy,
            "name": name,
            "lifetime": lifetime,
        },
    )
    pg = PlacementGroup(pg_id, bundles, strategy)
    if reply.get("infeasible_now"):
        # The reference keeps infeasible PGs pending so they are satisfied
        # when nodes join later (gcs_placement_group_manager); warn rather
        # than fail — ready() blocks until the cluster grows (or times out).
        import warnings

        pg.infeasible_now = True
        warnings.warn(
            f"placement group {pg_id.hex()[:8]} does not fit the current "
            f"cluster (bundles={bundles} strategy={strategy}); it will stay "
            "pending until nodes join",
            stacklevel=2,
        )
    # created or queued: either way the handle is valid; ready() blocks.
    return pg


def remove_placement_group(pg: PlacementGroup):
    _ensure_init()
    ctx.client.call("remove_placement_group", {"pg_id": pg.id.binary()})


# ------------------------------------------------------------- introspection


def cluster_resources() -> Dict[str, float]:
    _ensure_init()
    return ctx.client.call("cluster_resources")["resources"]


def available_resources() -> Dict[str, float]:
    _ensure_init()
    return ctx.client.call("available_resources")["resources"]


def nodes() -> List[dict]:
    _ensure_init()
    return ctx.client.call("list_state", {"kind": "nodes"})["items"]


def timeline() -> List[dict]:
    _ensure_init()
    return ctx.client.call("list_state", {"kind": "timeline"})["items"]


def task_events(task_id: Optional[str] = None,
                errors: bool = False) -> List[dict]:
    """Retained per-task lifecycle histories (SUBMITTED/SCHEDULED/RUNNING/
    FINISHED/FAILED transitions with timestamps, placement, and the full
    traceback on failure).  Failed-task records survive worker and node
    death — they live at the head."""
    _ensure_init()
    body: Dict[str, Any] = {"kind": "task_events"}
    if task_id:
        body["task_id"] = task_id
    if errors:
        body["errors"] = True
    return ctx.client.call("list_state", body)["items"]


def iter_log_chunks(call, proc_id: str, offset: int = 0,
                    max_bytes: int = -1, follow: bool = False,
                    poll_s: float = 0.5, chunk_bytes: int = 1 << 20):
    """Yield raw byte chunks of a process's log via repeated ``get_log``
    head RPCs — the one paging loop shared by :func:`get_log` and the CLI.
    ``call`` is any head-RPC callable (``Client.call``).  ``max_bytes >= 0``
    caps the TOTAL bytes yielded, in follow mode too; ``follow=True`` keeps
    polling a live process and stops once it is dead and drained."""
    off, remaining = offset, max_bytes
    while True:
        want = chunk_bytes if remaining < 0 else min(chunk_bytes, remaining)
        if want == 0:
            return
        reply = call(
            "get_log", {"proc_id": proc_id, "offset": off, "max_bytes": want}
        )
        if not reply.get("found"):
            raise RuntimeError(reply.get("error", f"no log for {proc_id!r}"))
        data = reply.get("data") or b""
        if data:
            off = reply.get("next_offset", off + len(data))
            if remaining > 0:
                remaining -= len(data)
            yield data
        if follow:
            if not data:
                if not reply.get("alive", False):
                    return  # dead and drained: nothing more can arrive
                time.sleep(poll_s)
        elif reply.get("eof", True) or not data:
            return


def get_log(proc_id: str, offset: int = 0, max_bytes: int = -1,
            follow: bool = False):
    """Fetch a process's log through the head's cluster log index — works
    from any machine, for live AND exited processes (crash post-mortems).

    ``proc_id`` is a worker/node id (hex, unique prefix ok), an actor id
    (resolves to its hosting worker), or a pid.  A negative ``offset``
    addresses from the end of the file (tail).  ``max_bytes=-1`` reads to
    EOF; ``max_bytes >= 0`` caps the total bytes read (follow included).
    With ``follow=True`` returns a generator that yields text chunks as
    the process writes (stops once the process is dead and the file is
    drained)."""
    _ensure_init()
    chunks = iter_log_chunks(ctx.client.call, proc_id, offset, max_bytes,
                             follow)
    if follow:
        return (c.decode("utf-8", "replace") for c in chunks)
    return b"".join(chunks).decode("utf-8", "replace")


def stack_dump(worker_id: str, timeout: float = 10.0) -> str:
    """All-thread Python stacks from a live worker (id/prefix, or an actor
    id), collected without interrupting the running task — the first tool
    to reach for when a gang hangs in a collective."""
    _ensure_init()
    reply = ctx.client.call(
        "stack_dump", {"worker_id": worker_id, "timeout": timeout},
        timeout=timeout + 30,
    )
    if not reply.get("found") or not reply.get("ok"):
        raise RuntimeError(reply.get("error", "stack dump failed"))
    return reply["dump"]
