"""Wire-schema versioning + message validation for the control plane.

Role-equivalent to the reference's protobuf schemas (reference:
src/ray/protobuf/*.proto — 22 files give every RPC a typed, versioned wire
format).  This framework ships msgpack dicts for flexibility; this module
supplies the two protections protobuf would have given:

- **Protocol version handshake**: every `register` carries
  ``PROTOCOL_VERSION``; the head rejects mismatched peers with a clear
  error instead of failing later on a missing/renamed field (the analog of
  a protobuf breaking-change guard).  Bump the version whenever a message's
  required fields change incompatibly.
- **Required-field validation**: the head validates the control-plane's
  mutating messages at the boundary and answers malformed ones with a
  field-level error, instead of a KeyError deep in a handler.

Only *requests into the head* are validated — responses and pushes are
produced by the head itself.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

PROTOCOL_VERSION = 1

_BYTES = (bytes, bytearray)
_NUM = (int, float)

#: method -> ((field, allowed types | None for any), ...)
REQUIRED: Dict[str, Tuple[Tuple[str, Any], ...]] = {
    # `register` doubles as the field-state RESYNC message (head restart
    # survival): with ``reconnect: true`` the body carries the process's
    # existing identity (worker_id/node_id/peer_addr) plus a ``resync``
    # map — workers: {actor_id, creation_spec (with actor_meta), running
    # _tasks}; nodes: {worker_pids, headless_s}.  The head adopts the
    # reported state or answers {"refused": reason}; object manifests
    # replay separately through put_object_batch entries (optionally
    # flagged ``resync: true`` to skip the adopt push-back).
    "register": (("kind", str),),
    "submit_task": (
        ("task_id", _BYTES),
        ("func_key", (str, type(None))),
        ("return_ids", list),
    ),
    "create_actor": (("actor_id", _BYTES), ("creation_task", dict)),
    "submit_actor_task": (("task_id", _BYTES), ("actor_id", _BYTES)),
    "task_done": (("task_id", _BYTES),),
    "put_object": (("object_id", _BYTES),),
    "put_object_batch": (("objects", list),),
    "proxy_put": (("object_id", _BYTES), ("total", _NUM), ("offset", _NUM),
                  ("data", _BYTES)),
    "object_free_ack": (("token", _NUM),),
    "get_objects": (("object_ids", list),),
    "next_stream_item": (("task_id", _BYTES), ("index", _NUM)),
    "pull_object": (("object_id", _BYTES),),
    "wait_objects": (("object_ids", list),),
    "object_sizes": (("object_ids", list),),
    "free_objects": (("object_ids", list),),
    "add_object_ref": (("object_ids", list),),
    "reconstruct_object": (("object_id", _BYTES),),
    "create_placement_group": (("pg_id", _BYTES), ("bundles", list)),
    "remove_placement_group": (("pg_id", _BYTES),),
    "kill_actor": (("actor_id", _BYTES),),
    "cancel_task": (("task_id", _BYTES),),
    "get_actor_by_name": (("name", str),),
    "kv_put": (("key", str), ("value", _BYTES)),
    "kv_get": (("key", str),),
    "kv_del": (("key", str),),
    "publish": (("topic", str),),
    "subscribe": (("topic", str),),
    "list_state": (("kind", str),),
    "batch": (("entries", list),),
    "stream_item": (("task_id", _BYTES), ("index", _NUM)),
    "task_blocked": (("task_id", _BYTES),),
    "task_unblocked": (("task_id", _BYTES),),
    "node_health_ack": (("node_id", _BYTES),),
    "node_stats": (("node_id", _BYTES),),
    "node_drain": (("node_id", _BYTES),),
    # Batched span plane: finished tracing spans ship in one body (each
    # entry needs trace_id/span_id/name; the handler skips malformed
    # entries instead of failing the batch).
    "span_batch": (("spans", list),),
    "metrics_report": (("pid", _NUM), ("rows", list)),
    "pg_ready": (("pg_id", _BYTES),),
    "read_log": (("path", str),),
    # Methods whose bodies carry no required fields still get a row: the
    # floor "body is a map" check applies, and rtlint RT003 treats a row
    # as the declaration that the method's wire shape is owned here.
    "worker_ready": (),
    "shutdown_cluster": (),
    "restore_object": (("object_id", _BYTES),),
    "get_log": (("proc_id", str),),
    "stack_dump": (("worker_id", str),),
    "stack_dump_reply": (("token", _NUM), ("dump", str)),
    # Flight recorder: batched engine step records (each entry needs
    # engine/step; the handler skips malformed entries like span_batch).
    "engine_step_batch": (("steps", list),),
    # Gang round flight recorder: batched per-rank training round records
    # (each entry needs gang/rank/round; malformed entries are skipped).
    "gang_round_batch": (("rounds", list),),
    # Device-memory accounting snapshot (util/devmem.py), shipped on the
    # worker's metrics cadence.
    "devmem_report": (("pid", _NUM), ("devmem", dict)),
    # On-demand profiler capture (stack_dump-shaped token round trip:
    # CLI -> head -> worker push -> profile_reply resolves the waiter).
    "profile": (("worker_id", str), ("seconds", _NUM)),
    "profile_reply": (("token", _NUM),),
    # -- dataplane: peer-to-peer calls + node-local task leases ---------------
    # resolve_actor is a pure read (idempotent) but keeps a row so the
    # address-resolution wire shape is owned here like every other method.
    "resolve_actor": (("actor_id", _BYTES),),
    "lease_request": (("resources", dict), ("count", _NUM)),
    "lease_return": (("lease_ids", list),),
    "lease_renew": (("lease_ids", list),),
    # Batched completion report for directly-executed tasks (telemetry +
    # task history; object registration rides the submitter's put batch).
    "direct_done": (("task_id", _BYTES),),
    # Worker-plane peer RPCs.  Their servers live in worker processes,
    # outside the head's _validated wrapper — the handlers validate these
    # rows in-handler, mirroring pull_object/read_log.
    "peer_submit": (("spec", dict), ("worker_id", _BYTES)),
    "peer_next_stream_item": (("task_id", _BYTES), ("index", _NUM),
                              ("worker_id", _BYTES)),
    "peer_cancel": (("task_id", _BYTES),),
}


class SchemaError(Exception):
    """Malformed control-plane message (missing/mistyped required field)."""


def validate(method: str, body: Any) -> None:
    """Raise SchemaError when ``body`` is missing required fields for
    ``method``.  Unknown methods and extra fields pass — the schema guards
    the floor, it does not freeze the ceiling (matching proto3's
    unknown-field tolerance)."""
    spec = REQUIRED.get(method)
    if spec is None:
        return
    if not isinstance(body, dict):
        raise SchemaError(
            f"{method}: body must be a map, got {type(body).__name__}"
        )
    for field, types in spec:
        if field not in body:
            raise SchemaError(f"{method}: missing required field {field!r}")
        if types is not None and not isinstance(body[field], types):
            tn = getattr(types, "__name__", None) or "/".join(
                t.__name__ for t in types
            )
            raise SchemaError(
                f"{method}: field {field!r} must be {tn}, got "
                f"{type(body[field]).__name__}"
            )


def check_protocol(peer_version: Any) -> None:
    """Reject peers speaking a different protocol revision."""
    if peer_version is None:
        # Pre-handshake tooling (old CLI builds): tolerate, the field
        # floor still validates individual messages.
        return
    if peer_version != PROTOCOL_VERSION:
        raise SchemaError(
            f"protocol version mismatch: peer speaks {peer_version}, this "
            f"head speaks {PROTOCOL_VERSION}; upgrade the older side"
        )
