"""Cluster resource scheduler: nodes, resource accounting, scheduling policies,
placement groups.

Role-equivalent to the reference's two-level scheduler
(reference: src/ray/raylet/scheduling/cluster_task_manager.h:42,
cluster_resource_scheduler.h:44, policy/hybrid_scheduling_policy.h:50,
policy/bundle_scheduling_policy.h) with TPU-first extensions: TPU chips and
pod-slice topology are first-class resources ("TPU", "TPU-v5p-128-head"
markers — reference behavior at python/ray/_private/accelerators/tpu.py:198),
and placement groups support gang ("slice") reservations so SPMD jobs get
all-or-nothing worker groups aligned to an ICI domain.

Pure in-memory logic — the control plane (head.py) drives it from its event
loop; no IO here, so it is unit-testable in isolation.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

from .ids import NodeID, PlacementGroupID

ResourceDict = Dict[str, float]

_EPS = 1e-9


def _fits(avail: ResourceDict, req: ResourceDict) -> bool:
    return all(avail.get(k, 0.0) + _EPS >= v for k, v in req.items())


def _sub(avail: ResourceDict, req: ResourceDict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def _add(avail: ResourceDict, req: ResourceDict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


class PlacementStrategy(str, enum.Enum):
    PACK = "PACK"
    SPREAD = "SPREAD"
    STRICT_PACK = "STRICT_PACK"
    STRICT_SPREAD = "STRICT_SPREAD"


@dataclasses.dataclass
class SchedulingStrategy:
    """Union of the reference's scheduling strategies
    (reference: python/ray/util/scheduling_strategies.py)."""

    kind: str = "default"  # default | spread | node_affinity | placement_group
    node_id: Optional[NodeID] = None
    soft: bool = False
    pg_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1

    @staticmethod
    def default() -> "SchedulingStrategy":
        return SchedulingStrategy()


@dataclasses.dataclass
class NodeState:
    node_id: NodeID
    total: ResourceDict
    available: ResourceDict
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    alive: bool = True
    # Announced preemption (SIGTERM with a grace window): the node is still
    # up — running work may finish and checkpoint — but no NEW leases or
    # bundle reservations land on it (reference: ray.util.state node DRAINING
    # via DrainNode; autoscaler v2 drains before terminating).
    draining: bool = False
    # Free TPU chip IDs on this host.  The float "TPU" resource governs
    # *admission*; this pool assigns the concrete device indices a granted
    # task may see (reference: tpu.py:155 TPU_VISIBLE_CHIPS isolation).
    tpu_free: List[int] = dataclasses.field(default_factory=list)
    # Execution slots leased out to clients for direct (head-bypassing)
    # task submission.  Their resources are held in `available` like any
    # running task's; this count keeps the load visible to introspection
    # and the autoscaler even though the per-task traffic never transits
    # the head (reference: raylet worker leases are resources in use).
    leased_slots: int = 0

    @property
    def schedulable(self) -> bool:
        """Eligible for NEW placements (alive and not being drained)."""
        return self.alive and not self.draining

    def utilization(self) -> float:
        worst = 0.0
        for k, tot in self.total.items():
            if tot <= 0:
                continue
            used = tot - self.available.get(k, 0.0)
            worst = max(worst, used / tot)
        return worst


@dataclasses.dataclass
class Bundle:
    resources: ResourceDict
    node_id: Optional[NodeID] = None  # where reserved
    available: ResourceDict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PlacementGroup:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: PlacementStrategy
    created: bool = False
    name: str = ""


class ClusterScheduler:
    """Resource bookkeeping + node selection for tasks, actors, and bundles."""

    def __init__(self, spread_threshold: float = 0.5):
        self.nodes: Dict[NodeID, NodeState] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroup] = {}
        self.spread_threshold = spread_threshold
        self._spread_rr = 0  # round-robin cursor for SPREAD strategy

    # -- node membership ------------------------------------------------------

    def add_node(
        self,
        node_id: NodeID,
        resources: ResourceDict,
        labels: Optional[Dict[str, str]] = None,
    ) -> NodeState:
        node = NodeState(
            node_id=node_id,
            total=dict(resources),
            available=dict(resources),
            labels=labels or {},
            tpu_free=list(range(int(resources.get("TPU", 0)))),
        )
        self.nodes[node_id] = node
        return node

    # -- TPU chip-ID pool -----------------------------------------------------

    def allocate_tpu_chips(self, node_id: NodeID, n: int) -> Optional[List[int]]:
        """Assign ``n`` concrete chip IDs on a node whose float "TPU"
        resources were already acquired.  Returns None when the pool is
        short (a blocked or retiring holder's process still maps the
        devices) — the dispatcher then refuses to dispatch and the task
        waits for a real chip (head._dispatch)."""
        node = self.nodes.get(node_id)
        if node is None or len(node.tpu_free) < n:
            return None
        chips = node.tpu_free[:n]
        del node.tpu_free[:n]
        return chips

    def free_tpu_chips(self, node_id: NodeID, chips: List[int]) -> None:
        node = self.nodes.get(node_id)
        if node is not None:
            node.tpu_free.extend(c for c in chips if c not in node.tpu_free)
            node.tpu_free.sort()

    def lease_slot(self, node_id: NodeID, resources: ResourceDict) -> bool:
        """Reserve one direct-submission execution slot on a node (the
        lease-table analog of a task acquire).  Draining/dead nodes never
        grant: a lease outliving the node would hand the client a doomed
        endpoint."""
        node = self.nodes.get(node_id)
        if node is None or not node.schedulable:
            return False
        if not _fits(node.available, resources):
            return False
        _sub(node.available, resources)
        node.leased_slots += 1
        return True

    def release_slot(self, node_id: NodeID, resources: ResourceDict) -> None:
        node = self.nodes.get(node_id)
        if node is not None:
            _add(node.available, resources)
            node.leased_slots = max(0, node.leased_slots - 1)

    def mark_draining(self, node_id: NodeID) -> bool:
        """Announced preemption: stop NEW placements on the node while its
        grace window runs.  Running work (and its resources) is untouched —
        the node-death path reclaims everything when the daemon exits."""
        node = self.nodes.get(node_id)
        if node is None:
            return False
        node.draining = True
        return True

    def remove_node(self, node_id: NodeID) -> List[PlacementGroupID]:
        """Drop a node.  Returns ids of placement groups that lost bundles
        (the control plane retries `reschedule_lost_bundles` for them —
        reference: gcs_placement_group_scheduler.h reschedules bundles on
        node death)."""
        node = self.nodes.pop(node_id, None)
        if node is None:
            return []
        damaged: List[PlacementGroupID] = []
        for pg in self.placement_groups.values():
            for b in pg.bundles:
                if b.node_id == node_id:
                    b.node_id = None  # bundle lost; pg needs reschedule
                    if pg.pg_id not in damaged:
                        damaged.append(pg.pg_id)
        return damaged

    def reschedule_lost_bundles(self, pg_id: PlacementGroupID) -> bool:
        """Re-place bundles whose node died.  Returns True when the PG is
        whole again (all bundles placed); False to retry later.  Placement
        honors the PG strategy: STRICT_SPREAD avoids nodes holding sibling
        bundles, STRICT_PACK co-locates with survivors (or re-packs from
        scratch when every bundle was lost)."""
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return True  # removed meanwhile
        lost = [b for b in pg.bundles if b.node_id is None]
        if not lost:
            return True
        used = {b.node_id for b in pg.bundles if b.node_id is not None}
        placed: List[Tuple[Bundle, NodeID]] = []
        avail = {
            nid: dict(n.available)
            for nid, n in self.nodes.items() if n.schedulable
        }
        for b in lost:
            order = sorted(
                avail,
                key=lambda nid: (self.nodes[nid].utilization(), nid),
            )
            chosen = None
            for nid in order:
                if pg.strategy == PlacementStrategy.STRICT_SPREAD and nid in used:
                    continue
                if (pg.strategy == PlacementStrategy.STRICT_PACK and used
                        and nid not in used):
                    continue
                if _fits(avail[nid], b.resources):
                    chosen = nid
                    break
            if chosen is None:
                return False  # all-or-nothing: retry when resources free up
            _sub(avail[chosen], b.resources)
            used.add(chosen)
            placed.append((b, chosen))
        for b, nid in placed:
            b.node_id = nid
            b.available = dict(b.resources)
            _sub(self.nodes[nid].available, b.resources)
        return True

    # -- task/actor placement -------------------------------------------------

    def pick_node(
        self,
        resources: ResourceDict,
        strategy: SchedulingStrategy | None = None,
    ) -> Optional[NodeID]:
        """Choose a feasible node.  Returns None if nothing fits right now."""
        strategy = strategy or SchedulingStrategy.default()

        if strategy.kind == "placement_group":
            return self._pick_in_pg(resources, strategy)

        if strategy.kind == "node_affinity":
            node = self.nodes.get(strategy.node_id)
            if node and node.schedulable and _fits(node.available, resources):
                return node.node_id
            if strategy.soft:
                return self._pick_hybrid(resources)
            return None

        alive = [n for n in self.nodes.values() if n.schedulable]
        if strategy.kind == "spread":
            # Round-robin over feasible nodes
            # (reference: scheduling/policy/spread_scheduling_policy.h).
            feasible = [n for n in alive if _fits(n.available, resources)]
            if not feasible:
                return None
            feasible.sort(key=lambda n: n.node_id)
            node = feasible[self._spread_rr % len(feasible)]
            self._spread_rr += 1
            return node.node_id

        return self._pick_hybrid(resources)

    def _pick_hybrid(self, resources: ResourceDict) -> Optional[NodeID]:
        """Hybrid policy: prefer packing onto already-utilized nodes while
        below spread_threshold, then prefer the least-utilized node."""
        feasible = [
            n
            for n in self.nodes.values()
            if n.schedulable and _fits(n.available, resources)
        ]
        if not feasible:
            return None

        def score(n: NodeState) -> Tuple:
            u = n.utilization()
            over = u >= self.spread_threshold
            # Below threshold: pack (higher utilization first).  Above: spread
            # (lower utilization first).  Node id breaks ties deterministically.
            return (over, -u if not over else u, n.node_id)

        return min(feasible, key=score).node_id

    def _pick_in_pg(
        self, resources: ResourceDict, strategy: SchedulingStrategy
    ) -> Optional[NodeID]:
        pg = self.placement_groups.get(strategy.pg_id)
        if pg is None or not pg.created:
            return None
        indices = (
            [strategy.bundle_index]
            if strategy.bundle_index >= 0
            else range(len(pg.bundles))
        )
        for i in indices:
            b = pg.bundles[i]
            if b.node_id is None or not _fits(b.available, resources):
                continue
            node = self.nodes.get(b.node_id)
            if node is None or not node.schedulable:
                # Draining/dead host: starting NEW work there would be
                # killed at grace-window end.  The task pends; the bundle
                # re-places via reschedule_lost_bundles when the node dies.
                continue
            return b.node_id
        return None

    def acquire(
        self,
        node_id: NodeID,
        resources: ResourceDict,
        strategy: SchedulingStrategy | None = None,
    ) -> bool:
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return False
        if strategy and strategy.kind == "placement_group":
            pg = self.placement_groups.get(strategy.pg_id)
            if pg is None:
                return False
            indices = (
                [strategy.bundle_index]
                if strategy.bundle_index >= 0
                else range(len(pg.bundles))
            )
            for i in indices:
                b = pg.bundles[i]
                if b.node_id == node_id and _fits(b.available, resources):
                    _sub(b.available, resources)
                    return True
            return False
        if not _fits(node.available, resources):
            return False
        _sub(node.available, resources)
        return True

    def acquire_force(
        self,
        node_id: NodeID,
        resources: ResourceDict,
        strategy: SchedulingStrategy | None = None,
    ) -> None:
        """Acquire without a feasibility check (availability may go negative).

        Used when a worker resumes from a blocked get/wait: its resources were
        released while it was parked so other tasks could run (reference:
        raylet releases CPU for workers blocked in ray.get), and on resume it
        must get them back even if that oversubscribes the node transiently —
        the deficit self-corrects as running tasks finish."""
        if strategy and strategy.kind == "placement_group":
            pg = self.placement_groups.get(strategy.pg_id)
            if pg is not None:
                indices = (
                    [strategy.bundle_index]
                    if strategy.bundle_index >= 0
                    else range(len(pg.bundles))
                )
                for i in indices:
                    b = pg.bundles[i]
                    if b.node_id == node_id:
                        _sub(b.available, resources)
                        return
            return
        node = self.nodes.get(node_id)
        if node is not None:
            _sub(node.available, resources)

    def check_feasible_ever(
        self, bundles: Sequence[ResourceDict], strategy: str
    ) -> bool:
        """Would these bundles fit on an *empty* cluster of the current
        nodes?  Distinguishes 'queue until resources free up' from 'can
        never be satisfied' for placement-group admission."""
        saved = {nid: n.available for nid, n in self.nodes.items()}
        try:
            for n in self.nodes.values():
                n.available = dict(n.total)
            probe = PlacementGroup(
                pg_id=PlacementGroupID.nil(),
                bundles=[Bundle(resources=dict(b)) for b in bundles],
                strategy=PlacementStrategy(strategy),
            )
            return self._place_bundles(probe) is not None
        finally:
            for nid, n in self.nodes.items():
                n.available = saved[nid]

    def release(
        self,
        node_id: NodeID,
        resources: ResourceDict,
        strategy: SchedulingStrategy | None = None,
    ) -> None:
        if strategy and strategy.kind == "placement_group":
            pg = self.placement_groups.get(strategy.pg_id)
            if pg is not None:
                indices = (
                    [strategy.bundle_index]
                    if strategy.bundle_index >= 0
                    else range(len(pg.bundles))
                )
                for i in indices:
                    b = pg.bundles[i]
                    if b.node_id == node_id:
                        _add(b.available, resources)
                        return
            return
        node = self.nodes.get(node_id)
        if node is not None:
            _add(node.available, resources)

    # -- placement groups -----------------------------------------------------

    def create_placement_group(
        self,
        pg_id: PlacementGroupID,
        bundles: Sequence[ResourceDict],
        strategy: str = "PACK",
        name: str = "",
    ) -> bool:
        """Reserve bundle resources.  All-or-nothing: on failure nothing is
        held (the reference runs a 2PC across raylets for this —
        gcs_placement_group_scheduler.h:117; with a single control plane the
        transaction is local but semantics match)."""
        strat = PlacementStrategy(strategy)
        pg = PlacementGroup(
            pg_id=pg_id,
            bundles=[Bundle(resources=dict(b)) for b in bundles],
            strategy=strat,
            name=name,
        )
        placed = self._place_bundles(pg)
        if placed is None:
            return False
        for b, node_id in zip(pg.bundles, placed):
            b.node_id = node_id
            b.available = dict(b.resources)
            _sub(self.nodes[node_id].available, b.resources)
        pg.created = True
        self.placement_groups[pg_id] = pg
        return True

    def _place_bundles(self, pg: PlacementGroup) -> Optional[List[NodeID]]:
        avail = {
            nid: dict(n.available)
            for nid, n in self.nodes.items()
            if n.schedulable
        }
        placed: List[NodeID] = []
        strat = pg.strategy

        if strat in (PlacementStrategy.PACK, PlacementStrategy.STRICT_PACK):
            order = sorted(
                avail, key=lambda nid: -self.nodes[nid].utilization()
            )
            for b in pg.bundles:
                chosen = None
                candidates = [placed[0]] if (
                    strat == PlacementStrategy.STRICT_PACK and placed
                ) else order
                for nid in candidates:
                    if _fits(avail[nid], b.resources):
                        chosen = nid
                        break
                if chosen is None:
                    return None
                _sub(avail[chosen], b.resources)
                placed.append(chosen)
            return placed

        # SPREAD / STRICT_SPREAD
        order = sorted(avail, key=lambda nid: self.nodes[nid].utilization())
        used: set = set()
        for b in pg.bundles:
            chosen = None
            for nid in order:
                if strat == PlacementStrategy.STRICT_SPREAD and nid in used:
                    continue
                if _fits(avail[nid], b.resources):
                    chosen = nid
                    break
            if chosen is None and strat == PlacementStrategy.SPREAD:
                for nid in order:
                    if _fits(avail[nid], b.resources):
                        chosen = nid
                        break
            if chosen is None:
                return None
            _sub(avail[chosen], b.resources)
            used.add(chosen)
            placed.append(chosen)
        return placed

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        pg = self.placement_groups.pop(pg_id, None)
        if pg is None:
            return
        for b in pg.bundles:
            if b.node_id is not None and b.node_id in self.nodes:
                # Return what the bundle still holds plus what tasks gave back.
                _add(self.nodes[b.node_id].available, b.resources)

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "nodes": {
                n.node_id.hex(): {
                    "total": n.total,
                    "available": n.available,
                    "labels": n.labels,
                    "alive": n.alive,
                    "draining": n.draining,
                    "leased_slots": n.leased_slots,
                }
                for n in self.nodes.values()
            },
            "placement_groups": {
                pg.pg_id.hex(): {
                    "strategy": pg.strategy.value,
                    "created": pg.created,
                    "bundles": [
                        {
                            "resources": b.resources,
                            "node": b.node_id.hex() if b.node_id else None,
                        }
                        for b in pg.bundles
                    ],
                }
                for pg in self.placement_groups.values()
            },
        }
