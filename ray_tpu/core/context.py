"""Process-global runtime context (driver or worker).

Analog of the reference's global worker singleton
(reference: python/ray/_private/worker.py global_worker).
"""

from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self):
        self.client = None  # core.client.Client
        self.mode: Optional[str] = None  # "driver" | "worker" | None
        self.job_id = None
        self.node_id = None
        self.worker_id = None
        self.session: Optional[str] = None
        self.current_task_id = None
        self.current_actor_id = None
        self.head_process = None  # in-driver head thread, if we started one
        self.namespace: str = "default"
        self.dashboard = None  # dashboard.Dashboard, if started via init()

    @property
    def initialized(self) -> bool:
        return self.client is not None

    def reset(self):
        self.__init__()


ctx = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return ctx
