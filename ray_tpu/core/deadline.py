"""Unified deadline/backoff policy for every RPC path.

Role-equivalent to the reference's retryable-RPC plumbing (reference:
src/ray/rpc/retryable_grpc_client.h — one client-level policy of timeouts
and exponential backoff shared by every GCS call, instead of per-call-site
constants).  Before this module, each path carried its own ad-hoc shape:
``client.call`` hard-coded base/cap constants, the node and worker
reconnect loops each re-implemented jittered doubling, and peer calls had
NO in-flight deadline at all (only ``peer_connect_timeout_s``, which covers
the dial).  Every retry loop now shares:

- :class:`BackoffPolicy` — jittered exponential backoff, built once from
  config (``rpc_retry_base_s`` / ``rpc_retry_cap_s``), same curve on every
  path.
- :class:`Deadline` — a monotonic per-call budget.  Threaded through head
  calls (``head_restart_retry_window_s``), peer calls
  (``peer_call_deadline_s``, enforced by the dataplane watchdog), and the
  reconnect loops (``head_reconnect_deadline_s``).  A budget rides a task
  spec as ``spec["deadline_s"]`` (remaining seconds at hand-off), so a
  direct call retried via the head cannot exceed the submitter's original
  budget.

The retry/deadline counters live here too so every consumer emits through
one literal-named site (rtlint RT006).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional

from .config import get_config


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Jittered exponential backoff: delay(n) is ``base * multiplier**(n-1)``
    capped at ``cap``, scaled by a uniform factor in [1-jitter, 1+jitter]
    (the de-synchronizer: a head restart must not see every client redial
    on the same tick)."""

    base_s: float = 0.05
    multiplier: float = 2.0
    cap_s: float = 0.5
    jitter: float = 0.5

    def delay(self, attempt: int) -> float:
        d = min(self.base_s * (self.multiplier ** max(0, attempt - 1)),
                self.cap_s)
        return d * (1.0 - self.jitter + 2.0 * self.jitter * random.random())

    def sleep(self, attempt: int, deadline: "Optional[Deadline]" = None):
        """Sleep the attempt's delay, clipped to the deadline's remainder."""
        d = self.delay(attempt)
        if deadline is not None:
            d = min(d, max(0.0, deadline.remaining()))
        if d > 0:
            time.sleep(d)


class Deadline:
    """A monotonic expiry: the per-call budget every retry loop checks
    instead of counting attempts against ad-hoc windows."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = expires_at

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(time.monotonic() + budget_s)

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def timeout(self, cap: Optional[float] = None) -> float:
        """A per-attempt timeout bounded by the remaining budget."""
        r = max(0.0, self.remaining())
        return r if cap is None else min(cap, r)


def call_policy() -> BackoffPolicy:
    """THE policy object: every RPC retry loop (idempotent head reads,
    reconnect loops, peer re-dials) backs off on this curve."""
    cfg = get_config()
    return BackoffPolicy(base_s=cfg.rpc_retry_base_s,
                         cap_s=cfg.rpc_retry_cap_s)


def reconnect_policy() -> BackoffPolicy:
    """Same curve, reconnect-scaled: redials of a down head start at 2x the
    call base and cap at the resync-grace-compatible 2 s (the head's
    ``head_resync_grace_s`` must exceed this cap for adoptions to win)."""
    cfg = get_config()
    return BackoffPolicy(base_s=max(0.1, 2 * cfg.rpc_retry_base_s),
                         cap_s=2.0)


# ------------------------------------------------------------------ metrics

_retry_counter = None
_deadline_counter = None


def count_retry(path: str):
    """One RPC attempt beyond the first, tagged by path ("head", "peer",
    "reconnect", "stream")."""
    global _retry_counter
    try:
        if _retry_counter is None:
            from ..util.metrics import get_counter

            _retry_counter = get_counter(
                "ray_tpu_rpc_retries_total",
                "RPC attempts beyond the first, by path",
                tag_keys=("path",),
            )
        _retry_counter.inc(1, tags={"path": path})
    except Exception:
        pass  # metrics must never fail a retry path


def count_deadline_exceeded(path: str):
    """A call abandoned because its deadline budget ran out."""
    global _deadline_counter
    try:
        if _deadline_counter is None:
            from ..util.metrics import get_counter

            _deadline_counter = get_counter(
                "ray_tpu_rpc_deadline_exceeded_total",
                "Calls abandoned at their deadline budget, by path",
                tag_keys=("path",),
            )
        _deadline_counter.inc(1, tags={"path": path})
    except Exception:
        pass
