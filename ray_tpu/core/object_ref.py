"""ObjectRef: a future handle to a task return or put object.

Reference analog: python/ray/includes/object_ref (Cython ObjectRef) — holds
the object id, supports get/wait, decrements the reference count on GC so the
control plane can free the underlying store segment.
"""

from __future__ import annotations

import threading
from typing import Optional

from . import serialization
from .context import ctx
from .ids import ObjectID
from ..devtools.locks import make_lock

# Batched free queue: ObjectRef.__del__ must never block on RPC — and must
# never call into Client methods at all: __del__ can run from cyclic GC
# inside a client critical section, so taking any client lock here can
# self-deadlock.  __del__ only appends and signals; the client's flusher
# thread does the actual work.
_free_lock = make_lock("objectref.free_queue")
_free_queue: list = []
flush_wanted = threading.Event()


def _flush_free_queue(background: bool = False):
    with _free_lock:
        batch, _free_queue[:] = _free_queue[:], []
    if batch and ctx.client is not None:
        try:
            if background:
                # __del__-triggered flushes must not block on a round trip;
                # the pipelined call keeps frees prompt so large freed
                # segments return to the store pool instead of forcing
                # eviction/spill of live objects.
                ctx.client.free_objects_bg(batch)
            else:
                ctx.client.free_objects(batch)
        except Exception:
            pass


class ObjectRef:
    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: ObjectID, owned: bool = True):
        self._id = object_id
        self._owned = owned

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    @property
    def object_id(self) -> ObjectID:
        return self._id

    def task_id(self):
        return self._id.task_id()

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __del__(self):
        # `ctx` can already be None during interpreter shutdown (module
        # globals cleared before the last refs are collected).
        client = ctx.client if ctx is not None else None
        if self._owned and client is not None:
            raw = self._id.binary()
            with _free_lock:
                _free_queue.append(raw)
            # Wake the client's flusher thread; large objects get a prompt
            # flush (their segments should return to the warm pool fast).
            if len(_free_queue) >= 16 or raw in client.large_oids:
                flush_wanted.set()

    def __reduce__(self):
        # Crossing a process boundary: the receiver holds a borrowed reference.
        # The sender bumps the count so the object outlives the transfer
        # (simplified borrowing vs reference_count.h's full protocol).
        if ctx.client is not None:
            # Direct-call results live only in the sender's local cache
            # until shared: register head-side first so the receiver's
            # get() has a record to seal against.
            ctx.client.ensure_shared(self._id.binary())
            ctx.client.add_reference(self._id.binary())
        return (_reconstruct_ref, (self._id.binary(),))

    # Allow `await ref` inside async actors.
    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, lambda: ctx.client.get([self])[0])
        return fut.__await__()


def _reconstruct_ref(raw: bytes) -> "ObjectRef":
    return ObjectRef(ObjectID(raw), owned=True)


class _TopLevelRef:
    """Marker for a top-level ObjectRef argument: resolved to its value before
    the task body runs (Ray semantics: top-level refs are awaited+inlined,
    nested refs are passed through as refs)."""

    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        self.raw = raw


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded objects
    (reference: python/ray/_raylet.pyx ObjectRefGenerator /
    core_worker.h:392 TryReadObjectRefStream)."""

    def __init__(self, task_id_bytes: bytes):
        self._task_id = task_id_bytes
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        item = ctx.client.next_stream_item(self._task_id, self._index)
        if item.get("done"):
            raise StopIteration
        if item.get("error") is not None:
            raise serialization.unpack(item["error"])
        self._index += 1
        return ObjectRef(ObjectID(item["object_id"]))

    def cancel(self, force: bool = False) -> None:
        """Cancel the producing task (reference: ray.cancel on a streaming
        generator's task).  The worker raises TaskCancelledError inside the
        generator body, which closes it — a token-streaming deployment
        frees its engine state mid-flight this way."""
        ctx.client.cancel_task(self._task_id, force)

    def __reduce__(self):
        return (ObjectRefGenerator, (self._task_id,))
