"""Node daemon: the per-host runtime for non-head nodes.

Role-equivalent to the reference's raylet main
(reference: src/ray/raylet/main.cc, node_manager.h:119) combined with the
object-manager transfer server (src/ray/object_manager/object_manager.h:117):

- registers the node (resources, labels, worker cap, store session, and the
  address of its object-plane server) with the head,
- owns the node's shared-memory ObjectStore (accounting, LRU eviction,
  spill/restore) for segments created by its workers,
- spawns worker processes when the head pushes ``spawn_worker`` (the lease
  protocol stays centralized in the head; this daemon is the arm that forks
  processes on the right host),
- serves chunked ``pull_object`` reads so any process in the cluster can
  fetch this node's objects over TCP (the analog of the reference's chunked
  object push/pull, object_manager.h:63 object_chunk_size).

Scheduling decisions stay in the head — a deliberate simplification vs the
reference's distributed raylet scheduler that a TPU cluster's scale profile
(hundreds of hosts, gang-scheduled jobs) tolerates well.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..devtools.locks import guarded, make_lock
from .config import get_config
from .ids import NodeID, ObjectID
from .object_store import ObjectStore
from .rpc import RpcClient, RpcServer, ServerThread

PULL_CHUNK_BYTES = 8 * 1024 * 1024

# Bulk-channel wire format: request = object_id | offset u64 | length u64;
# response = u64 byte count (NOT_FOUND sentinel if the object is gone)
# followed by that many raw bytes (server-side os.sendfile from the shm
# segment — zero user-space copies).
BULK_NOT_FOUND = 0xFFFF_FFFF_FFFF_FFFF


class BulkServer(threading.Thread):
    """Raw-TCP object reads: the data plane of the object manager.

    The msgpack RPC channel tops out well under 1 GiB/s on large frames
    (pack/unpack + asyncio stream copies); bulk transfers skip all of it —
    the server sendfile()s straight from the segment file and the client
    recv_into()s straight into its staged mmap (reference:
    object_manager.h:125-139 runs object chunks on dedicated rpc streams for
    the same reason).  One thread per connection; pullers hold one
    connection per remote node."""

    def __init__(self, store: ObjectStore, session: str, host: str):
        super().__init__(daemon=True, name="bulk-server")
        self._store = store
        self._session = session
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]

    def run(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="bulk-conn",
            ).start()

    def _serve(self, conn: socket.socket):
        from .object_store import _seg_path

        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        id_len = ObjectID.byte_len()
        try:
            while True:
                hdr = _recv_exact(conn, id_len + 16)
                if hdr is None:
                    return
                oid = ObjectID(hdr[:id_len])
                offset, length = struct.unpack_from("<QQ", hdr, id_len)
                # Pin first: a concurrent spill between get() and the open
                # below would unlink the segment and fail a live object.
                # The puller holds a reference so a free can't race us; pin
                # guards against spill eviction only.
                self._store.pin(oid)
                view = self._store.get(oid)  # restores from spill if needed
                if view is None:
                    self._store.unpin(oid)
                    conn.sendall(struct.pack("<Q", BULK_NOT_FOUND))
                    continue
                n = max(0, min(length, len(view) - offset))
                del view  # holding it would block pooling the segment later
                try:
                    fd = os.open(_seg_path(self._session, oid), os.O_RDONLY)
                except FileNotFoundError:
                    self._store.unpin(oid)
                    conn.sendall(struct.pack("<Q", BULK_NOT_FOUND))
                    continue
                try:
                    conn.sendall(struct.pack("<Q", n))
                    sent = 0
                    while sent < n:
                        sent += os.sendfile(
                            conn.fileno(), fd, offset + sent, n - sent
                        )
                    self._store.count_transferred(sent)
                finally:
                    os.close(fd)
                    self._store.unpin(oid)
        except (OSError, ConnectionError):
            pass
        finally:
            conn.close()

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int):
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


# Log files live under this root; ranged log reads refuse anything else so
# the read-log RPC can never be aimed at an arbitrary file.
LOG_ROOT = "/tmp/ray_tpu_logs"
LOG_READ_MAX_BYTES = 4 * 1024 * 1024


def own_log_path() -> str:
    """This process's own log file, for registration with the head's log
    index: the spawner exports RT_LOG_PATH; processes started with plain
    stdout redirection (node daemons under cluster_utils) discover it from
    /proc, restricted to the cluster log root."""
    path = os.environ.get("RT_LOG_PATH", "")
    if path:
        return path
    try:
        target = os.readlink("/proc/self/fd/1")
        if target.startswith(LOG_ROOT + os.sep) and os.path.isfile(target):
            return target
    except OSError:
        pass
    return ""


def read_log_range(path: str, offset=0, max_bytes=65536) -> dict:
    """Ranged read of a registered log file.  Negative offsets address from
    the end (tail); replies carry `next_offset` so callers can stream
    (`follow`) without re-reading.  Shared by the node daemon's `read_log`
    handler and the head (which reads its own node's files directly)."""
    real = os.path.realpath(path or "")
    # realpath BOTH sides: on hosts where /tmp is itself a symlink (macOS
    # /tmp -> /private/tmp), the literal root would never prefix-match.
    root = os.path.realpath(LOG_ROOT)
    if not real.startswith(root + os.sep):
        return {"found": False,
                "error": f"log path {path!r} is outside {LOG_ROOT}"}
    try:
        size = os.path.getsize(real)
        off = int(offset)
        if off < 0:
            off = max(0, size + off)
        n = max(0, min(int(max_bytes), LOG_READ_MAX_BYTES))
        with open(real, "rb") as f:
            f.seek(off)
            data = f.read(n)
    except OSError as e:
        return {"found": False, "error": f"cannot read {path}: {e}"}
    return {
        "found": True,
        "data": data,
        "offset": off,
        "next_offset": off + len(data),
        "size": size,
        "eof": off + len(data) >= size,
    }


def make_log_read_handler():
    """`read_log` for a node's RPC server: the head routes `get_log` calls
    for this node's processes here (head -> owning node -> file).  Like
    the pull handler, validates its own schema row — node servers sit
    outside the head's ``_validated`` wrapper."""

    async def h_read_log(conn, body):
        from . import schema as wire_schema
        from .rpc import RpcError

        try:
            wire_schema.validate("read_log", body)
        except wire_schema.SchemaError as e:
            raise RpcError(str(e)) from None
        return read_log_range(
            body.get("path", ""), body.get("offset", 0),
            body.get("max_bytes", 65536),
        )

    return h_read_log


def make_pull_handler(store: ObjectStore):
    """Chunked object reads from a node store.  Shared by the node daemon and
    the head (which serves its own local node's objects).  Validates its own
    schema row: pull servers register outside the head's ``_validated``
    wrapper, and the boundary guarantee must hold on every server that
    speaks the method."""

    async def h_pull_object(conn, body):
        from . import schema as wire_schema
        from .rpc import RpcError

        try:
            wire_schema.validate("pull_object", body)
        except wire_schema.SchemaError as e:
            raise RpcError(str(e)) from None
        oid = ObjectID(body["object_id"])
        view = store.get(oid)  # restores from spill if needed
        if view is None:
            return {"found": False}
        offset = body.get("offset", 0)
        max_bytes = body.get("max_bytes", PULL_CHUNK_BYTES)
        chunk = bytes(view[offset:offset + max_bytes])
        store.count_transferred(len(chunk))
        return {"found": True, "size": len(view), "data": chunk}

    return h_pull_object


@guarded
class NodeDaemon:
    # Worker bookkeeping is shared between the spawner thread, push
    # handlers on the head-connection rpc loop, and the main daemon loop:
    # rtlint RT007 verifies the guards statically, RT_DEBUG_LOCKS=2
    # asserts them at runtime.  head/node_id are write-once publications:
    # set before (or guarded against) any handler that reads them can run.
    _RT_GUARDED_BY = {
        "worker_pids": "_workers_lock",
        "worker_procs": "_workers_lock",
        "zygote": "_zygote_lock",
        "_reconnecting": "_reconnect_guard",
        "_headless_since": "_reconnect_guard",
        "headless_total_s": "_reconnect_guard",
    }
    _RT_UNGUARDED = {
        "head": "write-once in start() before any push handler is "
                "registered on it; afterwards only the single reconnect "
                "thread rebinds it (a racing reader uses the dying client "
                "once more and its call fails like the connection loss it "
                "is recovering from)",
        "node_id": "write-once after register(); the health-check lambda "
                   "guards the pre-registration None window",
        "_server_port": "write-once in start() before the head connection "
                        "exists; the reconnect thread (which re-reads it "
                        "for the re-register body) can only run after a "
                        "connection loss, which needs that connection",
    }

    def __init__(self):
        cfg = get_config()
        self.head_addr = os.environ["RT_HEAD_ADDR"]
        self.session = os.environ.get(
            "RT_NODE_SESSION", f"node-{os.urandom(6).hex()}"
        )
        self.resources = json.loads(os.environ.get("RT_NODE_RESOURCES", "{}"))
        self.labels = json.loads(os.environ.get("RT_NODE_LABELS", "{}"))
        if "TPU" not in self.resources:
            # Autodetect this host's chips and pod-slice topology (reference:
            # tpu.py:97-117 /dev/accel* scan; tpu.py:198 pod resources).
            from ray_tpu import accelerators

            self.resources.update(accelerators.node_resources())
            for k, v in accelerators.node_labels().items():
                self.labels.setdefault(k, v)
        self.num_workers = int(os.environ.get("RT_NODE_NUM_WORKERS", "4"))
        self.host = os.environ.get("RT_NODE_HOST", "127.0.0.1")
        self.store = ObjectStore(
            self.session, cfg.object_store_memory, cfg.spill_dir
        )
        self.server = RpcServer(host=self.host, name="node-server")
        self.server.register("pull_object", make_pull_handler(self.store))
        self.server.register("read_log", make_log_read_handler())
        self.server.register("ping", lambda conn, body: {"ok": True})
        self.server_thread = ServerThread(self.server)
        self.bulk_server = BulkServer(self.store, self.session, self.host)
        self.bulk_server.start()
        self.worker_procs: List[subprocess.Popen] = []
        self.worker_pids: set = set()  # zygote-forked (orphaned to init)
        self.zygote = None
        # worker_pids/worker_procs are touched from the spawner thread,
        # the rpc-loop push handlers (_on_kill_worker), and the main loop;
        # the zygote is swapped by start() and the spawner.  Cheap lock for
        # the former (list/set ops only); the zygote lock may be held for
        # a whole spawn handshake, so never take it on the rpc loop.
        self._workers_lock = make_lock("node.workers")
        self._zygote_lock = make_lock("node.zygote")
        from concurrent.futures import ThreadPoolExecutor

        self._spawn_exec = ThreadPoolExecutor(1, thread_name_prefix="spawner")
        self.node_id: Optional[NodeID] = None
        self.head: Optional[RpcClient] = None
        self._shutdown = threading.Event()
        # Announced preemption (SIGTERM): grace window before this daemon
        # actually exits.  During the window the node is DRAINING head-side
        # (no new leases) but running workers keep going so gangs can
        # checkpoint (reference: spot/maintenance preemption semantics —
        # SIGTERM, then SIGKILL after the grace period).
        self.drain_grace_s = float(os.environ.get("RT_DRAIN_GRACE_S", "5"))
        self._drain_requested = False
        self._drain_deadline: Optional[float] = None
        self._drain_min_wait = 1.0
        # Headless degraded mode: when the head connection drops, ONE
        # reconnect thread redials with backoff (workers keep executing,
        # the store keeps serving pulls) until re-registered or the suicide
        # deadline passes.  headless_total_s is cumulative across outages
        # (reported in node_stats and the resync register).
        self._reconnect_guard = make_lock("node.reconnect_guard")
        self._reconnecting = False
        self._headless_since: Optional[float] = None
        self.headless_total_s = 0.0
        self._server_port = 0

    def _install_push_handlers(self, client: RpcClient):
        client.on_push("spawn_worker", self._on_spawn_worker)
        client.on_push("kill_worker", self._on_kill_worker)
        client.on_push("free_objects", self._on_free_objects)
        client.on_push("adopt_object", self._on_adopt_object)
        client.on_push("shutdown", lambda b: self._shutdown.set())
        client.on_push(
            "health_check",
            lambda b: self.head.call_async(
                "node_health_ack", {"node_id": self.node_id.binary()}
            ) if self.node_id else None,
        )

    def _register_body(self) -> dict:
        from . import schema as wire_schema

        body = {
            "kind": "node",
            "protocol": wire_schema.PROTOCOL_VERSION,
            "resources": self.resources,
            "labels": self.labels,
            "num_workers": self.num_workers,
            "store_session": self.session,
            "object_addr": f"{self.host}:{self._server_port}",
            "bulk_addr": f"{self.host}:{self.bulk_server.port}",
            "pid": os.getpid(),
            "log_path": own_log_path(),
        }
        if self.node_id is not None:
            body["node_id"] = self.node_id.binary()
        elif os.environ.get("RT_NODE_ID"):  # pre-assigned (cluster_utils)
            body["node_id"] = bytes.fromhex(os.environ["RT_NODE_ID"])
        return body

    def start(self):
        self._server_port = self.server_thread.start()
        self.head = RpcClient(
            *self._split(self.head_addr), name="node-daemon-rpc"
        )
        self._install_push_handlers(self.head)
        self.head.on_connection_lost = self._on_head_lost
        reply = self.head.call("register", self._register_body())
        self.node_id = NodeID(reply["node_id"])
        # Boot the zygote eagerly so the first spawn request doesn't pay
        # the forkserver's one-time import cost.  Under the lock: a
        # spawn_worker push can arrive the moment register() returns, and
        # the spawner thread swaps self.zygote too — an unsynchronized
        # last-write-wins here would leak a live forkserver process.
        with self._zygote_lock:
            if self.zygote is None:
                try:
                    from .zygote import Zygote

                    self.zygote = Zygote(self._worker_env())
                except Exception:
                    self.zygote = None

    @staticmethod
    def _split(addr: str):
        host, port = addr.rsplit(":", 1)
        return host, int(port)

    # -- push handlers (run on the head-client rpc loop thread) ---------------

    def _worker_env(self):
        env = dict(os.environ)
        for k in list(env):
            if k.startswith(("PALLAS_AXON", "TPU_", "AXON_")):
                env.pop(k)
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_parent
        )
        env.update(
            RT_HEAD_ADDR=self.head_addr,
            RT_NODE_ID=self.node_id.hex(),
            RT_SESSION=self.session,
            # Peer-plane wiring: workers bind their peer RPC server on this
            # node's host.  (The node's object-plane endpoints travel via
            # the register body and head-side descriptors, not env.)
            RT_PEER_HOST=self.host,
            JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
        )
        return env

    def _on_spawn_worker(self, body):
        # Off-thread: this runs as a push handler on the head-client rpc
        # loop; the zygote handshake must not stall pushes.
        self._spawn_exec.submit(self._spawn_worker_blocking)

    def _spawn_worker_blocking(self):
        from .zygote import spawn_with_fallback

        env = self._worker_env()
        log_dir = os.path.join(LOG_ROOT, self.session)
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{time.time_ns()}.log")
        with self._zygote_lock:
            self.zygote, pid, proc = spawn_with_fallback(
                self.zygote, env, log_path
            )
        with self._workers_lock:
            if pid is not None:
                self.worker_pids.add(pid)
            else:
                self.worker_procs.append(proc)

    def _on_kill_worker(self, body):
        """SIGKILL a wedged local worker on the head's behalf — a stopped
        process can't run its connection-lost handler, so the daemon (which
        spawned it) must deliver the signal (reference: raylet DestroyWorker
        kills local worker processes)."""
        pid = body.get("pid")
        with self._workers_lock:
            ours = bool(pid) and (
                pid in self.worker_pids
                or any(p.pid == pid for p in self.worker_procs))
        if ours:
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def _on_free_objects(self, body):
        no_pool = set(body.get("no_pool", ()))
        for raw in body.get("object_ids", []):
            try:
                self.store.free(ObjectID(raw), pool=raw not in no_pool)
            except Exception:
                pass

    def _on_adopt_object(self, body):
        """Take accounting ownership of a segment a local worker created
        (the head routes this to the object's node)."""
        try:
            self.store.adopt(ObjectID(body["object_id"]))
        except (FileNotFoundError, MemoryError):
            pass

    # ------------------------------------------- headless mode / head restart

    def _on_head_lost(self):
        """Lost head connection (runs on the dying rpc loop thread): enter
        headless degraded mode instead of dying.  While headless, running
        workers keep executing (their own reconnect loops handle the head),
        the object store keeps serving pulls, and granted leases keep
        draining — only head-mediated ops (spawns, frees, stats) pause."""
        if self._shutdown.is_set() or self._drain_requested \
                or self._drain_deadline is not None:
            return  # already exiting: the run loop owns teardown
        with self._reconnect_guard:
            if self._reconnecting:
                return
            self._reconnecting = True
            self._headless_since = time.monotonic()
        threading.Thread(target=self._reconnect_loop, daemon=True,
                         name="head-reconnect").start()

    def _reconnect_loop(self):
        from . import deadline as _dl

        budget = get_config().head_reconnect_deadline_s
        deadline = _dl.Deadline.after(budget)
        policy = _dl.reconnect_policy()
        attempt = 0
        while not self._shutdown.is_set():
            if deadline.expired:
                _dl.count_deadline_exceeded("reconnect")
                print(
                    f"ray_tpu node daemon (session {self.session}): head "
                    f"did not return within {budget:.0f}s "
                    "(head_reconnect_deadline_s); shutting the node down",
                    file=sys.stderr, flush=True,
                )
                # The run loop's teardown SIGTERMs workers, closes the
                # zygote, and shuts the store — no orphaned processes.
                self._shutdown.set()
                return
            try:
                self._reconnect_once()
                with self._reconnect_guard:
                    self._reconnecting = False
                    if self._headless_since is not None:
                        self.headless_total_s += (
                            time.monotonic() - self._headless_since
                        )
                    self._headless_since = None
                return
            except Exception:
                pass
            attempt += 1
            _dl.count_retry("reconnect")
            policy.sleep(attempt, deadline)

    def _reconnect_once(self):
        """One redial + re-register carrying this node's field state; on
        success, swap the client and replay the store manifest so the
        restarted head rebuilds its object directory (rides the existing
        segment-adoption path in put_object_batch)."""
        client = RpcClient(
            *self._split(self.head_addr), name="node-daemon-rpc"
        )
        manifest = self.store.manifest()
        try:
            self._install_push_handlers(client)
            body = self._register_body()
            body["reconnect"] = True
            self._prune_worker_pids()
            with self._workers_lock:
                pids = list(self.worker_pids) + [
                    p.pid for p in self.worker_procs if p.poll() is None
                ]
            with self._reconnect_guard:
                headless_s = self.headless_total_s + (
                    (time.monotonic() - self._headless_since)
                    if self._headless_since is not None else 0.0
                )
            body["resync"] = {
                "worker_pids": pids,
                "headless_s": headless_s,
                "num_objects": len(manifest),
            }
            reply = client.call("register", body)
            self.node_id = NodeID(reply["node_id"])
            client.on_connection_lost = self._on_head_lost
        except BaseException:
            try:
                client.close()
            except Exception:
                pass
            raise
        old, self.head = self.head, client
        try:
            old.on_connection_lost = None
            old.close()
        except Exception:
            pass
        # Field-state resync, object half: every object this store can
        # still serve re-enters the head's directory (adopt path tolerates
        # already-known ids, so a plain blip just re-asserts records).
        node_raw = self.node_id.binary()
        for i in range(0, len(manifest), 2000):
            entries = [
                {"object_id": oid.binary(), "size": size,
                 "node_id": node_raw, "resync": True}
                for oid, size in manifest[i:i + 2000]
            ]
            client.call("put_object_batch", {"objects": entries})

    # ------------------------------------------------------------- draining

    def request_drain(self):
        """SIGTERM handler body: flag only.  The RPC announcing the drain
        runs from the main loop — a signal handler interrupting a call that
        holds the rpc client's non-reentrant lock must not re-enter it."""
        self._drain_requested = True

    def _begin_drain(self):
        """Report DRAINING to the head, keep serving for the grace window,
        then exit through the normal shutdown path (the head's disconnect
        handling does node-death cleanup)."""
        if self._drain_deadline is not None:
            return  # second SIGTERM: already draining
        self._drain_deadline = time.monotonic() + self.drain_grace_s
        # Zero workers at drain time: nothing can need the grace window —
        # just a short linger so the announce RPC flushes (the early-exit
        # check in run() uses this floor).
        self._prune_worker_pids()
        with self._workers_lock:
            had_workers = bool(self.worker_pids) or any(
                p.poll() is None for p in self.worker_procs
            )
        self._drain_min_wait = 1.0 if had_workers else 0.3
        try:
            self.head.call_async("node_drain", {
                "node_id": self.node_id.binary(),
                "grace_s": self.drain_grace_s,
            })
        except Exception:
            pass  # head gone: nothing to announce, just run out the grace

    # ------------------------------------------------------------------ loop

    def _prune_worker_pids(self):
        """Drop zygote-forked worker pids whose process is gone (orphans
        reaped by init): a stale pid could be recycled by an unrelated
        process and must never be signalled at shutdown."""
        with self._workers_lock:
            pids = list(self.worker_pids)
        for pid in pids:
            try:
                os.kill(pid, 0)
            except (ProcessLookupError, PermissionError):
                # Gone (or recycled by an unrelated uid): not ours anymore.
                with self._workers_lock:
                    self.worker_pids.discard(pid)

    def _report_stats(self):
        """Push this node's resource view to the head: store pressure, host
        load, live worker count (the resource-syncer role — reference:
        src/ray/common/ray_syncer/ray_syncer.h:88 gossips per-node resource
        views to the GCS over a bidi stream; here it rides the existing
        daemon connection)."""
        try:
            load1 = os.getloadavg()[0]
        except OSError:
            load1 = 0.0
        from .config import host_memory_used_frac

        with self._reconnect_guard:
            headless_s = self.headless_total_s + (
                (time.monotonic() - self._headless_since)
                if self._headless_since is not None else 0.0
            )
        stats = {
            "node_id": self.node_id.binary(),
            "store": self.store.stats(),
            "load1": load1,
            "mem_used_frac": host_memory_used_frac(),
            "num_worker_procs": (
                len(self.worker_pids) + len(self.worker_procs)  # rt-unguarded: len() snapshot for best-effort stats
            ),
            # Cumulative seconds this daemon has spent without a head
            # connection (surfaced as the per-node
            # ray_tpu_headless_seconds gauge head-side).
            "headless_s": headless_s,
        }
        try:
            self.head.call_async("node_stats", stats)
        except Exception:
            pass  # reporting is best-effort; liveness has its own path

    def run(self):
        ticks = 0
        while not self._shutdown.wait(timeout=0.2):
            if self._drain_requested and self._drain_deadline is None:
                self._begin_drain()
            if self._drain_deadline is not None:
                if time.monotonic() >= self._drain_deadline:
                    break  # grace window over: the preemption lands now
                # Early exit: once the last worker process is gone there is
                # nothing left to grace (the head shuts down IDLE workers
                # at drain, so an idle node clears out in ~a second while a
                # gang-hosting node runs its full window).
                self._prune_worker_pids()
                with self._workers_lock:
                    live_procs = [p for p in self.worker_procs
                                  if p.poll() is None]
                    no_workers = not self.worker_pids and not live_procs
                if (no_workers
                        and time.monotonic() >=
                        self._drain_deadline - self.drain_grace_s
                        + self._drain_min_wait):
                    break
            self.store.tick()  # cooled freed segments -> warm pool
            # Reap exited worker processes so they don't zombie.
            with self._workers_lock:
                procs = list(self.worker_procs)
            for p in procs:
                p.poll()
            ticks += 1
            if ticks % 10 == 0:
                self._report_stats()
                self._prune_worker_pids()
        with self._workers_lock:
            procs = list(self.worker_procs)
            pids = list(self.worker_pids)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        with self._zygote_lock:
            if self.zygote is not None:
                self.zygote.close()
        # Sweep this node's session-scoped fn-table blob cache (workers
        # populate /tmp/ray_tpu_fncache/<session>; the head's sweep only
        # covers its own host's filesystem).
        try:
            import shutil

            shutil.rmtree(
                os.path.join("/tmp/ray_tpu_fncache", self.session),
                ignore_errors=True,
            )
        except Exception:
            pass
        self.store.shutdown()
        os._exit(0)


def main():
    import faulthandler

    faulthandler.register(signal.SIGUSR1)
    daemon = NodeDaemon()
    # Preemption notice: SIGTERM starts a graceful drain instead of killing
    # the daemon outright (SIGKILL remains the crash-simulation path).
    signal.signal(signal.SIGTERM, lambda *_: daemon.request_drain())
    daemon.start()
    daemon.run()


if __name__ == "__main__":
    sys.exit(main())
