"""Worker process: executes tasks and hosts actor instances.

Role-equivalent to the reference's worker-side core worker
(reference: src/ray/core_worker/core_worker.h:350 RunTaskExecutionLoop,
transport/task_receiver.h, concurrency_group_manager.h for actor
concurrency, _raylet.pyx:1693 execute_task) — re-designed: tasks arrive as
pushes over one ordered connection from the control plane (which gives
per-actor FIFO for free), execution happens on a thread pool (or an asyncio
loop for async actors), results go inline or to node shared memory.

Workers are spawned with JAX_PLATFORMS=cpu by default so they never steal the
TPU from the SPMD job that owns it; a task opts into the chip by requesting
{"TPU": n} resources, which the spawner translates into TPU visibility env
vars (the reference does the same dance with TPU_VISIBLE_CHIPS at
python/ray/_private/accelerators/tpu.py:155).
"""

from __future__ import annotations

import asyncio
import ctypes
import inspect
import os
import queue
import sys
import threading
import time
import traceback
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import cloudpickle

from .. import exceptions
from . import serialization
from ..devtools.locks import guarded, make_lock
from .client import Client
from .config import get_config
from .context import ctx
from .ids import ActorID, ObjectID, TaskID
from .object_ref import ObjectRef, _TopLevelRef

_DEBUG_PUSH = bool(os.environ.get("RT_DEBUG_PUSH"))


@guarded
class _LogTee:
    """Mirrors a worker stream to the driver via pubsub (reference:
    _private/log_monitor.py tails worker logs and republishes to the driver
    over GCS pubsub; here the worker pushes lines itself)."""

    # print() runs on every task thread concurrently: the line buffer AND
    # the in-flight publish window are shared state (rtlint RT007;
    # RT_DEBUG_LOCKS=2 asserts the guards at runtime).
    _RT_GUARDED_BY = {
        "_buf": "_buf_lock",
        "_inflight": "_buf_lock",
        "dropped": "_buf_lock",
    }

    def __init__(self, stream, client, kind: str):
        self._stream = stream
        self._client = client
        self._kind = kind
        self._buf = ""
        self._buf_lock = make_lock("worker.log_tee")
        self._local = threading.local()
        # Own in-flight window: log lines must never poison the client's
        # shared bg-error channel or block a task — past the window they
        # drop (the log file keeps the full copy).
        self._inflight: list = []
        self.dropped = 0
        self._drop_counter = None  # resolved lazily, once, on first drop

    def write(self, s):
        n = self._stream.write(s)
        if getattr(self._local, "publishing", False):
            return n  # a publish-path print must not recurse
        lines = []
        with self._buf_lock:
            self._buf += s
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                if line.strip():
                    lines.append(line)
        for line in lines:
            self._local.publishing = True
            try:
                with self._buf_lock:
                    self._inflight = [
                        f for f in self._inflight if not f.done()
                    ]
                    drop = len(self._inflight) >= 200
                    if drop:
                        self.dropped += 1
                if drop:
                    # Head is behind: drop rather than block — but visibly
                    # (the drop count ships with the process metrics, so a
                    # chatty worker outrunning the head is diagnosable).
                    try:
                        if self._drop_counter is None:
                            from ray_tpu.util.metrics import get_counter

                            self._drop_counter = get_counter(
                                "ray_tpu_logs_dropped_total",
                                "worker log lines dropped past the "
                                "in-flight publish window (the log file "
                                "keeps them)",
                                tag_keys=("stream",),
                            )
                        self._drop_counter.inc(tags={"stream": self._kind})
                    except Exception:
                        pass
                    continue
                fut = self._client.rpc.call_async(
                    "publish", {
                        "topic": "worker_logs",
                        "data": {"pid": os.getpid(), "stream": self._kind,
                                 "actor": ctx.current_actor_id.hex()[:8]
                                 if ctx.current_actor_id else None,
                                 "line": line},
                    }
                )
                with self._buf_lock:
                    self._inflight.append(fut)
            except Exception:
                pass
            finally:
                self._local.publishing = False
        return n

    def flush(self):
        self._stream.flush()

    def flush_residual(self, timeout: float = 1.0):
        """Ship a trailing partial line (no newline) at worker shutdown —
        without this, a final ``print(..., end="")`` before exit never
        reaches the driver."""
        with self._buf_lock:
            line, self._buf = self._buf, ""
        if not line.strip():
            return
        self._local.publishing = True
        try:
            self._client.rpc.call_async("publish", {
                "topic": "worker_logs",
                "data": {"pid": os.getpid(), "stream": self._kind,
                         "actor": ctx.current_actor_id.hex()[:8]
                         if ctx.current_actor_id else None,
                         "line": line},
            }).result(timeout=timeout)
        except Exception:
            pass
        finally:
            self._local.publishing = False

    def __getattr__(self, name):
        return getattr(self._stream, name)


@guarded
class Worker:
    # rtlint RT007 verifies these statically; RT_DEBUG_LOCKS=2 asserts the
    # guards on field rebinds at runtime (devtools.locks).
    _RT_GUARDED_BY = {
        "direct_streams": "_streams_lock",
        "_direct_replies": "_direct_replies_lock",
        "_direct_replies_scheduled": "_direct_replies_lock",
        "_reconnecting": "_reconnect_guard",
        "_done_cache": "_dedup_lock",
        "_dedup_running": "_dedup_lock",
    }
    # Intentional cross-thread handoffs, vetted per CONTRIBUTING's
    # thread-role model: each is either ordered by the task queue (the
    # actor-creation task strictly precedes any concurrently-dispatched
    # method call) or a GIL-atomic monotonic best-effort signal.
    _RT_UNGUARDED = {
        "fn_cache": "content-addressed idempotent cache: a racing double "
                    "load stores the same value twice",
        "actor_creation_spec": "written by the actor-creation task (task "
                               "queue orders it before method dispatch); "
                               "the reconnect thread only reads it",
        "running_threads": "GIL-atomic dict set/pop keyed by task_id; "
                           "readers (cancel, stack dump) are best-effort",
        "cancelled": "GIL-atomic monotonic set.add; a cancel losing the "
                     "race is indistinguishable from arriving late",
        "actor_instance": "written by the actor-creation task, which the "
                          "task queue orders before any method dispatch",
        "actor_id": "creation-ordered (see actor_instance); the peer "
                    "server treats a mid-boot None as a stale route",
        "max_concurrency": "creation-ordered (see actor_instance)",
        "out_of_order": "creation-ordered (see actor_instance)",
        "method_groups": "creation-ordered (see actor_instance)",
        "_group_limits": "creation-ordered (see actor_instance)",
        "group_pools": "creation-ordered (see actor_instance)",
        "async_loop": "only the run-loop thread dispatches async methods, "
                      "so the lazy loop boot never races itself",
        "_async_group_sems": "dispatched from the run-loop thread only "
                             "(see async_loop)",
    }

    def __init__(self):
        from .node_main import own_log_path
        from .rpc import RpcServer, ServerThread

        self.head_addr = os.environ["RT_HEAD_ADDR"]
        self.node_id = bytes.fromhex(os.environ["RT_NODE_ID"])
        self.worker_id = os.urandom(16)
        # Peer RPC server: the direct-dataplane endpoint.  Drivers (and
        # other workers) submit actor calls and leased tasks HERE, never
        # through the head (reference: core_worker.proto PushTask — core
        # workers push tasks to each other directly).  Started before
        # registration so the head learns the address atomically with the
        # worker record; zygote-forked workers therefore come up with a
        # live peer endpoint before their first lease/call.
        self.direct_streams: Dict[bytes, dict] = {}
        # Stream state is shared between the peer-server loop (submit /
        # item pulls) and the executing task's thread (item appends,
        # completion marks): every direct_streams access holds this.
        self._streams_lock = make_lock("worker.streams")
        peer_host = os.environ.get("RT_PEER_HOST", "127.0.0.1")
        self.peer_server = RpcServer(host=peer_host, name="peer-server")
        self.peer_server.register("peer_submit", self.h_peer_submit)
        self.peer_server.register("peer_next_stream_item",
                                  self.h_peer_next_stream_item)
        self.peer_server.register("peer_cancel", self.h_peer_cancel)
        self.peer_thread = ServerThread(self.peer_server)
        peer_port = self.peer_thread.start()
        # Direct-reply coalescing: completions buffer here and one
        # call_soon_threadsafe per batch wakes the peer loop (the self-pipe
        # wakeup is a syscall; per-completion wakeups would bound direct
        # throughput at ~1k/s on sandboxed kernels).
        self._direct_replies: list = []
        self._direct_replies_lock = make_lock("worker.direct_replies")
        self._direct_replies_scheduled = False
        self.client = Client(
            self.head_addr,
            kind="worker",
            worker_id=self.worker_id,
            node_id=self.node_id,
            pid=os.getpid(),
            # Object writes go under this worker's node store session (set
            # by the node daemon / head spawner), not the head's.
            session=os.environ.get("RT_SESSION"),
            # Cluster log index entry: `get_log` serves this file from any
            # machine, even after this process dies.
            log_path=own_log_path(),
            peer_addr=f"{peer_host}:{peer_port}",
        )
        ctx.client = self.client
        ctx.mode = "worker"
        ctx.session = self.client.session
        ctx.worker_id = self.worker_id

        self.task_queue: "queue.Queue" = queue.Queue()
        self.fn_cache: Dict[str, Any] = {}
        self.actor_instance = None
        # Retained actor-creation spec: the field-state report this worker
        # carries when it re-registers with a restarted head — enough for
        # the head to rebuild a full-fidelity ActorRecord (adoption) for
        # the live actor instead of recreating it fresh.
        self.actor_creation_spec: Optional[dict] = None
        self.actor_id: Optional[bytes] = None
        self.max_concurrency = 1
        self.pool: Optional[ThreadPoolExecutor] = None
        self.group_pools: Dict[str, ThreadPoolExecutor] = {}
        self.method_groups: Dict[str, str] = {}
        self._group_limits: Dict[str, int] = {}
        self._async_group_sems: Dict[str, Any] = {}
        self.out_of_order = False
        self.async_loop: Optional[asyncio.AbstractEventLoop] = None
        self.running_threads: Dict[bytes, int] = {}  # task_id -> thread ident
        self.cancelled: set = set()
        # Duplicate-delivery dedup: retries and re-routes (a direct call
        # degraded to the head path after its reply was lost, a head
        # re-dispatch across a partition) may deliver the SAME task_id
        # twice.  Completed results are cached (bounded, oldest-first
        # eviction) and replayed instead of re-executed; a duplicate of a
        # STILL-RUNNING task parks until the original completes
        # (reference: task_id-keyed dedup in the reference's actor task
        # submission — the receiver, not the network, owns exactly-once).
        self._dedup_lock = make_lock("worker.dedup")
        self._done_cache: "OrderedDict[bytes, dict]" = OrderedDict()
        self._dedup_running: Dict[bytes, list] = {}
        self._shutdown = threading.Event()

        def _on_exec(spec):
            if _DEBUG_PUSH:
                print(f"PUSH execute_task {spec.get('name')} "
                      f"{spec['task_id'].hex()[:8]}", file=sys.stderr,
                      flush=True)
            self.task_queue.put(spec)

        self.client.rpc.on_push("execute_task", _on_exec)
        self.client.rpc.on_push("cancel", self._on_cancel)
        self.client.rpc.on_push("shutdown", lambda b: self._shutdown.set())
        # Head-initiated kill: exit through the clean-shutdown drain (log
        # tees' trailing partial line + final metrics window) instead of a
        # bare os._exit that drops them.  On a fresh thread — the drain
        # fires RPCs and must not run on (and block) the rpc loop itself.
        self.client.rpc.on_push(
            "exit",
            lambda b: threading.Thread(
                target=self._exit_with_drain, args=(1,), daemon=True,
                name="exit-drain",
            ).start(),
        )
        # Liveness probe: ack from the rpc loop thread (call_async is safe
        # there; a blocking call would deadlock the loop).  A wedged
        # interpreter stops acking and the head reaps us.
        self.client.rpc.on_push(
            "health_check",
            lambda b: self.client.rpc.call_async("health_ack", {}),
        )
        # On-demand introspection: dump all-thread Python stacks without
        # touching the running task (collection happens on the rpc loop
        # thread — the tool you reach for when a gang hangs in a
        # collective; reference: `ray stack` attaches py-spy, here the
        # worker cooperates via sys._current_frames).
        self.client.rpc.on_push("stack_dump", self._on_stack_dump)
        # On-demand profiler capture (`ray_tpu profile`): same token round
        # trip as stack_dump, but the capture sleeps for N seconds — it
        # runs on a fresh thread so the rpc loop keeps serving pushes.
        self.client.rpc.on_push("profile", self._on_profile)
        # Headless degraded mode: a lost head connection starts a reconnect
        # loop instead of killing the process — in-flight tasks, direct
        # peer calls, and peer streaming keep executing; completion reports
        # buffer in the client and replay at re-register.  The deadline
        # guarantees an orphaned worker (head never restarted) still dies.
        self._reconnect_guard = make_lock("worker.reconnect_guard")
        self._reconnecting = False
        self.client.resync_payload = self._resync_payload
        self.client.rpc.on_connection_lost = self._on_head_lost
        # Stream this worker's stdout/stderr to the driver (log files keep
        # the full copy); RT_LOG_TO_DRIVER=0 disables.
        if os.environ.get("RT_LOG_TO_DRIVER", "1") != "0":
            sys.stdout = _LogTee(sys.stdout, self.client, "stdout")
            sys.stderr = _LogTee(sys.stderr, self.client, "stderr")
        # Device-memory accounting: ship a util/devmem snapshot on the
        # metrics cadence.  maybe_snapshot() returns None until jax is
        # actually imported, so CPU-only task workers pay nothing.
        threading.Thread(target=self._devmem_loop, daemon=True,
                         name="devmem-report").start()
        # Handshake: only now may the head lease us (push handlers installed).
        self.client.call("worker_ready", {})

    # ---------------------------------------------------------------- loading

    def _load(self, key: str):
        obj = self.fn_cache.get(key)
        if obj is None:
            blob = self._load_blob_cached(key)
            if blob is None:
                raise RuntimeError(f"function table has no entry {key}")
            obj = cloudpickle.loads(blob)
            self.fn_cache[key] = obj
        return obj

    def _load_blob_cached(self, key: str):
        """Function-table blob with a node-local content-addressed file
        cache: an actor burst forks many fresh workers that all need the
        same class blob — the first fetch pays the head roundtrip, the
        rest read the session's cache dir (reference:
        gcs_function_manager.h function table + the runtime-env URI cache
        pattern).  Session-scoped so the head's teardown sweep bounds
        growth and concurrent clusters/users never share a directory."""
        import hashlib

        session = getattr(self.client, "session", None) or "default"
        cdir = os.path.join("/tmp/ray_tpu_fncache", session)
        path = os.path.join(
            cdir, hashlib.sha1(key.encode()).hexdigest()[:24])
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            pass
        blob = self.client.kv_get(key)
        if blob is not None:
            try:
                os.makedirs(cdir, exist_ok=True)
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.rename(tmp, path)
            except OSError:
                pass
        return blob

    def _resolve_args(self, spec) -> tuple:
        if spec.get("args_ref") is not None:
            oid = ObjectID(spec["args_ref"])
            # Through get(): local-store hits and lost-object recovery apply
            # to spilled-arg payloads just like user-level gets.
            args, kwargs = self.client.get([ObjectRef(oid, owned=False)])[0]
        else:
            args, kwargs = serialization.unpack(spec["args"])
        # Resolve top-level refs to values.
        fetch = [a.raw for a in args if isinstance(a, _TopLevelRef)]
        fetch += [v.raw for v in kwargs.values() if isinstance(v, _TopLevelRef)]
        if fetch:
            refs = [ObjectRef(ObjectID(raw), owned=False) for raw in fetch]
            values = dict(zip(fetch, self.client.get(refs)))
            args = tuple(
                values[a.raw] if isinstance(a, _TopLevelRef) else a for a in args
            )
            kwargs = {
                k: values[v.raw] if isinstance(v, _TopLevelRef) else v
                for k, v in kwargs.items()
            }
        return args, kwargs

    def _setup_py_modules(self, keys) -> list:
        """Extract content-addressed module archives and put their import
        roots on sys.path (reference: runtime_env/py_modules.py — each
        module ships as its own URI-cached package)."""
        import io
        import zipfile

        roots = []
        for key in keys:
            _, name, digest = key.split(":", 2)
            root = os.path.join("/tmp/ray_tpu_pymod", digest)
            dest = os.path.join(root, name)
            if not os.path.isdir(dest):
                blob = self.client.kv_get(key)
                if blob is None:
                    raise RuntimeError(f"py_module archive {key} not found")
                tmp = dest + f".tmp-{os.getpid()}"
                with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                    zf.extractall(tmp)
                os.makedirs(root, exist_ok=True)
                try:
                    os.rename(tmp, dest)
                except OSError:  # raced another worker: theirs is identical
                    import shutil

                    shutil.rmtree(tmp, ignore_errors=True)
            if root not in sys.path:
                sys.path.insert(0, root)
                roots.append(root)
        return roots

    def _setup_pip_env(self, pip_env: dict):
        """Build (once, content-addressed) and activate a per-env venv
        (reference: _private/runtime_env/pip.py — virtualenv per env hash,
        uri_cache.py for reuse).  The venv is created with
        --system-site-packages so framework deps stay importable; shipped
        wheel files install with --no-index (zero-egress clusters), named
        requirements go through pip's normal resolution.  Activation
        prepends the venv's site-packages to sys.path and exports
        VIRTUAL_ENV/PATH for user subprocesses; returns the site dir (the
        caller treats it like a py_modules root: removed + module-evicted
        on task teardown)."""
        import fcntl
        import subprocess
        import venv as venv_mod

        env_hash = pip_env["hash"]
        root = os.path.join("/tmp/ray_tpu_envs", env_hash)
        venv_dir = os.path.join(root, "venv")
        site = os.path.join(
            venv_dir, "lib",
            f"python{sys.version_info[0]}.{sys.version_info[1]}",
            "site-packages",
        )
        ready = os.path.join(root, "READY")  # -> (site_dir, venv_dir)
        if not os.path.exists(ready):
            os.makedirs(root, exist_ok=True)
            with open(os.path.join(root, ".lock"), "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if not os.path.exists(ready):
                    venv_mod.create(venv_dir, system_site_packages=True,
                                    with_pip=False, symlinks=True)
                    os.makedirs(site, exist_ok=True)
                    wheel_dir = os.path.join(root, "wheels")
                    os.makedirs(wheel_dir, exist_ok=True)
                    for key, base in pip_env.get("wheel_keys", []):
                        blob = self.client.kv_get(key)
                        if blob is None:
                            raise RuntimeError(
                                f"pip wheel {key} not found in cluster KV")
                        with open(os.path.join(wheel_dir, base), "wb") as f:
                            f.write(blob)
                    args, all_local = [], True
                    for entry in pip_env["reqs"]:
                        if entry[0] == "file":
                            args.append(os.path.join(wheel_dir, entry[1]))
                        else:
                            args.append(entry[1])
                            all_local = False
                    if args:
                        cmd = [sys.executable, "-m", "pip", "install",
                               "--quiet", "--target", site,
                               "--find-links", wheel_dir]
                        if all_local:
                            cmd.append("--no-index")
                        proc = subprocess.run(
                            cmd + args, capture_output=True, text=True,
                            timeout=600,
                        )
                        if proc.returncode != 0:
                            raise RuntimeError(
                                f"pip env build failed:\n{proc.stderr[-2000:]}")
                    with open(ready, "w") as f:
                        f.write("ok")
        if site not in sys.path:
            sys.path.insert(0, site)
        return site, venv_dir

    def _setup_conda_env(self, conda_env: dict):
        """Create (once, content-addressed) and activate a conda env
        (reference: _private/runtime_env/conda.py:260 — env created from a
        spec dict via the conda CLI, cached by content hash; named envs
        activate in place).  Activation mirrors the pip path: the env's
        site-packages joins sys.path (module eviction on teardown) and
        bin/ prepends PATH for subprocesses; the worker's interpreter is
        NOT swapped — a different-python conda env carries its packages,
        not its binary (documented limitation; the reference execs the
        env's python for that).  Returns (site_dir_or_None, prefix)."""
        import fcntl
        import glob as _glob
        import shutil
        import subprocess

        conda = shutil.which("conda")
        if conda is None:
            raise RuntimeError(
                "runtime_env['conda'] requested but no `conda` executable "
                "is on PATH for the worker")
        if "name" in conda_env:
            name = conda_env["name"]
            if os.path.isdir(name):
                prefix = name
            else:
                root = subprocess.run(
                    [conda, "info", "--base"], capture_output=True,
                    text=True, timeout=60,
                ).stdout.strip()
                prefix = os.path.join(root, "envs", name)
            if not os.path.isdir(prefix):
                raise RuntimeError(f"conda env {name!r} not found")
        else:
            env_hash = conda_env["hash"]
            root = os.path.join("/tmp/ray_tpu_envs", f"conda-{env_hash}")
            prefix = os.path.join(root, "env")
            ready = os.path.join(root, "READY")
            if not os.path.exists(ready):
                os.makedirs(root, exist_ok=True)
                with open(os.path.join(root, ".lock"), "w") as lock:
                    fcntl.flock(lock, fcntl.LOCK_EX)
                    if not os.path.exists(ready):
                        spec_path = os.path.join(root, "environment.json")
                        with open(spec_path, "w") as f:
                            f.write(conda_env["spec"])
                        proc = subprocess.run(
                            [conda, "env", "create", "-p", prefix,
                             "-f", spec_path, "--yes"],
                            capture_output=True, text=True, timeout=1800,
                        )
                        if proc.returncode != 0:
                            raise RuntimeError(
                                "conda env create failed:\n"
                                f"{proc.stderr[-2000:]}")
                        with open(ready, "w") as f:
                            f.write("ok")
        sites = _glob.glob(os.path.join(
            prefix, "lib", "python*", "site-packages"))
        site = sites[0] if sites else None
        if site is not None and site not in sys.path:
            sys.path.insert(0, site)
        return site, prefix

    def _setup_working_dir(self, key: str):
        """Extract a content-addressed working_dir archive (cached per key)
        and enter it (reference: runtime_env/working_dir.py — URI-cached
        package, extracted and prepended to sys.path)."""
        dest = os.path.join("/tmp/ray_tpu_wd", key.split(":", 1)[1])
        if not os.path.isdir(dest):
            import io
            import zipfile

            blob = self.client.kv_get(key)
            if blob is None:
                raise RuntimeError(f"working_dir archive {key} not found")
            tmp = dest + f".tmp-{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, dest)
            except OSError:  # raced another worker: theirs is identical
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        os.chdir(dest)
        if dest not in sys.path:
            sys.path.insert(0, dest)
        return dest

    # -------------------------------------------------------------- reporting

    def _store_value(self, oid: ObjectID, value) -> dict:
        cfg = get_config()
        meta, buffers = serialization.serialize(value)
        size = serialization.packed_size(meta, buffers)
        if size <= cfg.inline_object_max_bytes:
            blob = bytearray(size)
            serialization.pack_into(meta, buffers, memoryview(blob))
            return {"object_id": oid.binary(), "inline": bytes(blob)}
        buf = self.client.store().create(oid, size)
        serialization.pack_into(meta, buffers, buf)
        return {"object_id": oid.binary(), "size": size}

    def _report_done(self, spec, returns=None, error=None, retryable=False,
                     error_repr="", error_tb="", stream_count=0,
                     _replay=False):
        parked: list = []
        if not _replay:
            with self._dedup_lock:
                if error is None or not retryable:
                    # Retryable errors are NOT cached: the head re-issues a
                    # failed-retryable task under the SAME task_id, and a
                    # cached error would wrongly short-circuit the retry.
                    self._done_cache[spec["task_id"]] = {
                        "returns": returns or [], "error": error,
                        "retryable": retryable, "error_repr": error_repr,
                        "error_tb": error_tb, "stream_count": stream_count,
                    }
                    while len(self._done_cache) > 1024:
                        self._done_cache.popitem(last=False)
                parked = self._dedup_running.pop(spec["task_id"], [])
        direct_reply = spec.pop("_direct_reply", None)
        if direct_reply is not None:
            self._reply_direct(spec, direct_reply, returns or [], error,
                               retryable, error_repr, error_tb, stream_count)
        else:
            body = {
                "task_id": spec["task_id"],
                "returns": returns or [],
                "stream_count": stream_count,
            }
            if error is not None:
                body["error"] = error
                body["retryable"] = retryable
                body["error_repr"] = error_repr
                # Full traceback text: retained in the head's task-event
                # history so post-hoc debugging doesn't need the (possibly
                # unserializable or already-freed) exception object.
                body["error_tb"] = error_tb
                body["returns"] = [
                    {"object_id": raw} for raw in spec.get("return_ids", [])
                ]
            try:
                # Pipelined + batched: the worker moves on without a round
                # trip, and a burst of completions coalesces into one head
                # RPC; the run loop flushes when its queue drains
                # (reference: PushTask replies carry results
                # asynchronously).
                self.client.call_batched("task_done", body)
                if self.task_queue.empty():
                    # No follow-up work: the caller is blocking on this
                    # result.
                    self.client._flush_submit_batch()
                if _DEBUG_PUSH:
                    print(f"DONE-SENT {spec.get('name')} "
                          f"{spec['task_id'].hex()[:8]}", file=sys.stderr,
                          flush=True)
            except Exception:
                if _DEBUG_PUSH:
                    print(f"DONE-FAIL {spec.get('name')}: "
                          f"{traceback.format_exc()}", file=sys.stderr,
                          flush=True)
                os._exit(1)
        # Duplicates that arrived while this task ran: answer them with the
        # SAME completion — never a second execution.
        for dup in parked:
            self._report_done(dup, returns=returns, error=error,
                              retryable=retryable, error_repr=error_repr,
                              error_tb=error_tb, stream_count=stream_count,
                              _replay=True)

    def _reply_direct(self, spec, direct_reply, returns, error, retryable,
                      error_repr, error_tb, stream_count):
        """Complete a peer-submitted task: the result travels BACK over the
        peer connection (the submitter seals it locally and owns the object
        registration), while a batched ``direct_done`` report keeps the
        head's task history, timeline, and actor accounting complete —
        telemetry without per-call dispatch."""
        loop, fut = direct_reply
        body = {
            "returns": returns,
            "stream_count": stream_count,
            "session": self.client.session,
            "node_id": self.node_id,
        }
        if error is not None:
            body["error"] = error
            body["retryable"] = retryable
            body["error_repr"] = error_repr
            body["error_tb"] = error_tb
        with self._streams_lock:
            st = self.direct_streams.get(spec["task_id"])
            if st is not None:
                st["done"] = stream_count
                if error is not None:
                    st["error"] = error

        with self._direct_replies_lock:
            self._direct_replies.append((fut, body))
            wake = not self._direct_replies_scheduled
            if wake:
                self._direct_replies_scheduled = True
        if wake:
            try:
                loop.call_soon_threadsafe(self._drain_direct_replies)
            except RuntimeError:
                pass  # peer loop shutting down with the process
        done = {
            "task_id": spec["task_id"],
            "name": spec.get("name", ""),
            "failed": error is not None,
            "start": spec.get("_exec_start", 0.0),
            "end": time.time(),
        }
        if spec.get("actor_id"):
            done["actor_id"] = spec["actor_id"]
        if error is not None:
            done["error_repr"] = error_repr
            done["error_tb"] = error_tb
        try:
            # Batched background report — the run loop's idle flush and the
            # client's safety-net flusher bound its latency; nothing blocks
            # on it (the caller already has the result).
            self.client.call_batched("direct_done", done)
        except Exception:
            pass

    def _drain_direct_replies(self):
        """Peer loop thread: resolve every buffered completion (their
        ``h_peer_submit`` coroutines then send responses, which the
        Connection's write coalescer folds into one socket write).  Loops
        until observed empty with the flag still claimed so a completion
        racing the drain never pays a second wakeup."""
        while True:
            with self._direct_replies_lock:
                batch, self._direct_replies = self._direct_replies, []
                if not batch:
                    self._direct_replies_scheduled = False
                    return
            for fut, body in batch:
                if not fut.done():
                    fut.set_result(body)

    # -- peer dataplane server (direct actor calls + leased submissions) ------

    @staticmethod
    def _peer_validate(method: str, body):
        """In-handler schema validation: peer servers register outside the
        head's ``_validated`` wrapper, mirroring pull_object/read_log — the
        boundary guarantee must hold on every server speaking the method."""
        from . import schema as wire_schema
        from .rpc import RpcError

        try:
            wire_schema.validate(method, body)
        except wire_schema.SchemaError as e:
            raise RpcError(str(e)) from None

    async def h_peer_submit(self, conn, body):
        """Direct task submission from a peer (driver or another worker).
        The spec enters the same task queue head-pushed specs use, so
        arrival order — per-connection FIFO — is execution order for sync
        actors, and the reply resolves when the task completes."""
        self._peer_validate("peer_submit", body)
        if body["worker_id"] != self.worker_id:
            # Stale route: the caller resolved an address this process no
            # longer answers for (recycled port after a restart, confused
            # cache).  Refuse — executing would run on the wrong worker.
            return {"stale": True}
        spec = body["spec"]
        if spec.get("actor_id") and spec["actor_id"] != self.actor_id:
            # Stale incarnation: this process never hosted (or no longer
            # hosts) that actor — the caller must re-resolve via the head.
            return {"stale": True}
        with self._dedup_lock:
            rec = self._done_cache.get(spec["task_id"])
        if rec is not None:
            # Duplicate delivery (reply lost, submitter re-routed or the
            # injector duplicated the request): answer from the completion
            # cache — the task must not run twice.
            reply = {
                "returns": rec["returns"],
                "stream_count": rec["stream_count"],
                "session": self.client.session,
                "node_id": self.node_id,
            }
            if rec["error"] is not None:
                reply["error"] = rec["error"]
                reply["retryable"] = rec["retryable"]
                reply["error_repr"] = rec["error_repr"]
                reply["error_tb"] = rec["error_tb"]
            return reply
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        spec["_direct_reply"] = (loop, fut)
        if spec.get("num_returns") == "streaming":
            with self._streams_lock:
                if len(self.direct_streams) > 256:
                    # Bound retained stream state: shed fully-reported
                    # streams whose consumer never drained to the end.
                    for tid in list(self.direct_streams):
                        if self.direct_streams[tid]["done"] is not None:
                            del self.direct_streams[tid]
                        if len(self.direct_streams) <= 256:
                            break
                self.direct_streams[spec["task_id"]] = {
                    "items": [], "done": None, "error": None,
                }
        self.task_queue.put(spec)
        return await fut

    async def h_peer_next_stream_item(self, conn, body):
        """Direct-result streaming: the submitter pulls a streaming task's
        yielded items straight from the executing worker (head path analog:
        h_next_stream_item)."""
        self._peer_validate("peer_next_stream_item", body)
        if body["worker_id"] != self.worker_id:
            return {"stale": True}
        task_id = body["task_id"]
        index = int(body["index"])
        while True:
            # Brief hold per poll; released before the await (RT002).
            with self._streams_lock:
                st = self.direct_streams.get(task_id)
                if st is None:
                    return {"done": True}
                if index < len(st["items"]):
                    return {"item": st["items"][index]}
                if st["error"] is not None:
                    return {"error": st["error"]}
                if st["done"] is not None:
                    # Fully consumed: drop the retained stream state.
                    self.direct_streams.pop(task_id, None)
                    return {"done": True}
            await asyncio.sleep(0.005)

    async def h_peer_cancel(self, conn, body):
        self._peer_validate("peer_cancel", body)
        self._on_cancel(body)
        return {"cancelled": True}

    # -------------------------------------------------------------- execution

    def _execute(self, spec):
        task_id = spec["task_id"]
        # Duplicate-delivery gate: a completed task_id replays its cached
        # completion; a dup of a STILL-RUNNING task parks and is answered
        # by the original's _report_done.  Either way: no second execution.
        with self._dedup_lock:
            rec = self._done_cache.get(task_id)
            if rec is None:
                if task_id in self._dedup_running:
                    self._dedup_running[task_id].append(spec)
                    return
                self._dedup_running[task_id] = []
        if rec is not None:
            self._report_done(spec, returns=rec["returns"],
                              error=rec["error"],
                              retryable=rec["retryable"],
                              error_repr=rec["error_repr"],
                              error_tb=rec["error_tb"],
                              stream_count=rec["stream_count"],
                              _replay=True)
            return
        if _DEBUG_PUSH:
            print(f"EXEC start {spec.get('name')} {task_id.hex()[:8]}",
                  file=sys.stderr, flush=True)
        spec["_exec_start"] = time.time()
        ctx.current_task_id = TaskID(task_id)
        self.running_threads[task_id] = threading.get_ident()
        saved_env: Dict[str, Optional[str]] = {}
        saved_cwd: Optional[str] = None
        saved_wd_path: Optional[str] = None
        pymod_roots: list = []
        async_dispatched = False
        # Tracing: install the submitter's span context so user spans and
        # nested submissions inside this task become children (reference:
        # tracing_helper.py wraps execution in the propagated span).
        trace_token = None
        trace_start = 0.0
        injected = spec.get("trace_ctx")
        if injected is not None:
            from ray_tpu.util import tracing

            trace_token = tracing.set_context({
                "trace_id": injected["trace_id"],
                "span_id": injected.get("task_span_id")
                or injected["span_id"],
            })
            trace_start = time.time()
        try:
            if task_id in self.cancelled:
                raise exceptions.TaskCancelledError(TaskID(task_id).hex())
            renv = spec.get("runtime_env") or {}
            env_vars = renv.get("env_vars") or {}
            saved_env = {k: os.environ.get(k) for k in env_vars}
            for k, v in env_vars.items():
                os.environ[k] = v
            if spec.get("tpu_chips") is not None:
                # Chip grant from the scheduler: narrow this process's TPU
                # view before user code first imports jax (reference:
                # tpu.py:155 set_current_process_visible_accelerator_ids runs
                # in the worker at task start).  Takes effect only when jax
                # has not initialized its backend in this process yet — chip
                # tasks should land on fresh workers (dedicated actor
                # processes do by construction).
                from ray_tpu import accelerators

                tpu_keys = ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_HOST_BOUNDS",
                            "TPU_HOST_BOUNDS", "JAX_PLATFORMS")
                for k in tpu_keys:
                    saved_env.setdefault(k, os.environ.get(k))
                accelerators.apply_visibility(spec["tpu_chips"])
            if renv.get("working_dir_key"):
                saved_cwd = os.getcwd()
                saved_wd_path = self._setup_working_dir(
                    renv["working_dir_key"]
                )
            if renv.get("py_module_keys"):
                pymod_roots = self._setup_py_modules(renv["py_module_keys"])
            if renv.get("pip_env"):
                site, venv_dir = self._setup_pip_env(renv["pip_env"])
                # The venv site behaves like a py_modules root from here:
                # teardown removes it from sys.path and evicts its modules.
                pymod_roots.append(site)
                vbin = os.path.join(venv_dir, "bin")
                for k, v in (("VIRTUAL_ENV", venv_dir),
                             ("PATH", vbin + os.pathsep
                              + os.environ.get("PATH", ""))):
                    saved_env.setdefault(k, os.environ.get(k))
                    os.environ[k] = v
            if renv.get("conda_env"):
                site, prefix = self._setup_conda_env(renv["conda_env"])
                if site is not None:
                    pymod_roots.append(site)
                cbin = os.path.join(prefix, "bin")
                for k, v in (("CONDA_PREFIX", prefix),
                             ("PATH", cbin + os.pathsep
                              + os.environ.get("PATH", ""))):
                    saved_env.setdefault(k, os.environ.get(k))
                    os.environ[k] = v

            if spec.get("is_actor_creation"):
                cls = self._load(spec["func_key"])
                args, kwargs = self._resolve_args(spec)
                self.actor_instance = cls(*args, **kwargs)
                # Retained for head-restart resync: the re-register report
                # ships this spec so a restarted head can adopt the live
                # actor (wire-clean copy: internal "_" keys stripped).
                self.actor_creation_spec = {
                    k: v for k, v in spec.items() if not k.startswith("_")
                }
                self.actor_id = spec["actor_id"]
                ctx.current_actor_id = ActorID(self.actor_id)
                self.max_concurrency = spec.get("max_concurrency", 1)
                self.out_of_order = bool(spec.get("execute_out_of_order"))
                groups = spec.get("concurrency_groups") or {}
                self.method_groups = spec.get("method_groups") or {}
                self._group_limits = dict(groups)
                # Per-group executors isolate workloads: a saturated group
                # never blocks another group's dispatch (reference:
                # concurrency_group_manager.h — one fiber/thread pool per
                # named group, plus the default group).
                self.group_pools = {
                    name: ThreadPoolExecutor(
                        limit, thread_name_prefix=f"cg-{name}")
                    for name, limit in groups.items()
                }
                if self.max_concurrency > 1 or self.group_pools \
                        or self.out_of_order:
                    # With groups (or unordered execution) the default
                    # lane must also be pool-dispatched — inline execution
                    # would block the dispatch loop and stall every group.
                    # The pool never exceeds max_concurrency: out-of-order
                    # actors get reordered DISPATCH (head-side, see
                    # Head._drain_actor_queue), not extra execution threads,
                    # so unsynchronized actor state cannot race beyond what
                    # the user opted into.
                    self.pool = ThreadPoolExecutor(
                        max(self.max_concurrency, 1),
                        thread_name_prefix="cg-default")
                self._report_done(
                    spec,
                    returns=[self._store_value(
                        ObjectID(spec["return_ids"][0]), None)],
                )
                return

            if spec.get("method_name"):
                fn = getattr(self.actor_instance, spec["method_name"])
            else:
                fn = self._load(spec["func_key"])
            args, kwargs = self._resolve_args(spec)

            if inspect.iscoroutinefunction(
                fn.__func__ if inspect.ismethod(fn) else fn
            ):
                if os.environ.get("RT_DEBUG_PUSH"):
                    print(f"ASYNC-DISPATCH {spec.get('name')} {spec['task_id'].hex()[:8]}",
                          file=sys.stderr, flush=True)
                async_dispatched = True
                self._execute_async(spec, fn, args, kwargs)
                return

            result = fn(*args, **kwargs)

            if spec.get("num_returns") == "streaming":
                direct = "_direct_reply" in spec
                count = 0
                for item in result:
                    oid = ObjectID.for_task_return(TaskID(task_id), count + 1000)
                    info = self._store_value(oid, item)
                    if direct:
                        # Peer-submitted stream: items stay here and the
                        # submitter pulls them via peer_next_stream_item —
                        # no per-item head traffic.
                        with self._streams_lock:
                            st = self.direct_streams.get(task_id)
                            if st is not None:
                                st["items"].append(info)
                    else:
                        self.client.call_bg(
                            "stream_item",
                            {"task_id": task_id, "index": count, **info},
                        )
                    count += 1
                self._report_done(spec, returns=[], stream_count=count)
                return

            self._finish_ok(spec, result)
        except BaseException as e:  # noqa: BLE001 — all errors cross the wire
            if _DEBUG_PUSH:
                print(f"EXEC-ERR {spec.get('name')} {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
            try:
                self._finish_err(spec, e)
            except BaseException:  # noqa: BLE001 — a lost task_done hangs
                # the caller forever; report with a plain-string error even
                # when serializing the real one failed.
                self._report_done(
                    spec, error=serialization.pack(
                        exceptions.TaskError(RuntimeError(repr(e)), "")
                    ),
                    error_repr=repr(e),
                )
        finally:
            # Actor processes keep their runtime_env; pooled task workers
            # restore so env vars / cwd / sys.path don't leak into unrelated
            # tasks.  (The module import cache can still carry working_dir
            # modules across tasks — matching the reference's per-worker
            # caching semantics; distinct envs should use distinct workers.)
            if self.actor_instance is None:
                for k, old in saved_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                if saved_cwd is not None:
                    try:
                        os.chdir(saved_cwd)
                    except OSError:
                        pass
                    if saved_wd_path in sys.path:
                        sys.path.remove(saved_wd_path)
                for root in pymod_roots:
                    if root in sys.path:
                        sys.path.remove(root)
                if pymod_roots:
                    # Evict modules imported from the py_modules roots: a
                    # pooled worker may later receive a DIFFERENT version of
                    # the same module name (distinct content-addressed root),
                    # and a stale sys.modules hit would silently run old
                    # code — and leak shipped modules to env-less tasks.
                    for name, mod in list(sys.modules.items()):
                        f = getattr(mod, "__file__", None) or ""
                        if any(f.startswith(r + os.sep) or f == r
                               for r in pymod_roots):
                            del sys.modules[name]
            if injected is not None:
                from ray_tpu.util import tracing

                tracing.reset_context(trace_token)
                if not async_dispatched:
                    # Async actor methods emit their span from the coroutine
                    # itself (the dispatch thread returns immediately).
                    span = tracing.task_span(spec, trace_start, time.time())
                    if span is not None:
                        tracing.emit_span(span)
            self.running_threads.pop(task_id, None)
            ctx.current_task_id = None
            if _DEBUG_PUSH:
                print(f"EXEC end {spec.get('name')} {task_id.hex()[:8]}",
                      file=sys.stderr, flush=True)

    def _finish_ok(self, spec, result):
        num_returns = spec.get("num_returns", 1)
        return_ids = spec.get("return_ids", [])
        if num_returns == 1 or len(return_ids) == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != len(return_ids):
                raise ValueError(
                    f"task declared {len(return_ids)} returns but produced "
                    f"{len(values)}"
                )
        returns = [
            self._store_value(ObjectID(raw), v)
            for raw, v in zip(return_ids, values)
        ]
        self._report_done(spec, returns=returns)

    def _finish_err(self, spec, e: BaseException):
        # From the exception object, not format_exc(): some callers reach
        # here OUTSIDE an except block (unknown-concurrency-group paths),
        # where format_exc() yields the garbage "NoneType: None".
        tb = "".join(
            traceback.format_exception(type(e), e, e.__traceback__)
        )
        if isinstance(e, exceptions.RayTpuError):
            wrapped = e
        else:
            wrapped = exceptions.TaskError(e, tb)
        try:
            blob = serialization.pack(wrapped)
        except Exception:
            blob = serialization.pack(
                exceptions.TaskError(RuntimeError(repr(e)), tb)
            )
        retryable = bool(spec.get("retry_exceptions")) and not isinstance(
            e, exceptions.TaskCancelledError
        )
        self._report_done(
            spec, error=blob, retryable=retryable, error_repr=repr(e),
            error_tb=tb,
        )

    def _execute_async(self, spec, fn, args, kwargs):
        """Async actor method: run as a coroutine on the actor's event loop,
        concurrently with other async methods (reference: fiber.h /
        actor_scheduling_queue async mode)."""
        if self.async_loop is None:
            self.async_loop = asyncio.new_event_loop()
            threading.Thread(
                target=self.async_loop.run_forever, daemon=True,
                name="actor-async-loop",
            ).start()

        injected = spec.get("trace_ctx")
        # Concurrency groups apply to async methods too (reference:
        # fiber.h — one fiber pool per group): an asyncio.Semaphore per
        # group caps in-flight coroutines.  Created lazily on the loop
        # thread's behalf; sized from the creation-time declaration.
        group = spec.get("concurrency_group") \
            or self.method_groups.get(spec.get("method_name", ""))
        sem = None
        if group is not None:
            limit = self._group_limits.get(group)
            if limit is None:
                self._finish_err(spec, ValueError(
                    f"unknown concurrency group {group!r}"))
                return
            sems = getattr(self, "_async_group_sems", None)
            if sems is None:
                sems = self._async_group_sems = {}
            sem = sems.get(group)
            if sem is None:
                sem = sems[group] = asyncio.Semaphore(limit)

        async def run():
            # Tracing: the span must cover the coroutine's real lifetime and
            # the context must live on THIS (event-loop) thread so nested
            # spans/submissions inside the method parent correctly — the
            # dispatching thread's context is useless here.
            token = None
            start = 0.0
            if injected is not None:
                from ray_tpu.util import tracing

                token = tracing.set_context({
                    "trace_id": injected["trace_id"],
                    "span_id": injected.get("task_span_id")
                    or injected["span_id"],
                })
                start = time.time()
            try:
                if sem is not None:
                    async with sem:
                        result = await fn(*args, **kwargs)
                else:
                    result = await fn(*args, **kwargs)
                self._finish_ok(spec, result)
            except BaseException as e:  # noqa: BLE001
                self._finish_err(spec, e)
            finally:
                if injected is not None:
                    from ray_tpu.util import tracing

                    tracing.reset_context(token)
                    span = tracing.task_span(spec, start, time.time())
                    if span is not None:
                        tracing.emit_span(span)

        asyncio.run_coroutine_threadsafe(run(), self.async_loop)

    # ------------------------------------------- headless mode / head restart

    def _resync_payload(self) -> dict:
        """Field-state report carried on a reconnect register: the hosted
        actor (with its full creation spec, so a restarted head can rebuild
        a full-fidelity record and adopt the LIVE instance) plus the tasks
        still executing here (for observability)."""
        out: Dict[str, Any] = {
            "running_tasks": list(self.running_threads.keys()),
        }
        if self.actor_id is not None:
            out["actor_id"] = self.actor_id
            spec = self.actor_creation_spec
            if spec is not None:
                out["creation_spec"] = spec
                meta = spec.get("actor_meta") or {}
                if meta.get("name"):
                    out["actor_name"] = meta["name"]
        return out

    def _on_head_lost(self):
        """Lost head connection (runs on the dying rpc loop thread): enter
        headless degraded mode.  One reconnect thread, claim-then-act."""
        if self._shutdown.is_set():
            # Already shutting down: exit now, but through the same drain
            # (trailing log line + final metrics) every other exit takes.
            self._exit_with_drain(0)
        with self._reconnect_guard:
            if self._reconnecting:
                return
            self._reconnecting = True
        threading.Thread(target=self._reconnect_loop, daemon=True,
                         name="head-reconnect").start()

    def _reconnect_loop(self):
        """Redial the head with jittered backoff until re-registered or the
        suicide deadline passes.  While this runs, the execution side keeps
        working: task threads run, peer_submit keeps accepting direct
        calls, and completed head-routed reports buffer in the client for
        replay at re-register."""
        from . import deadline as _dl

        budget = get_config().head_reconnect_deadline_s
        deadline = _dl.Deadline.after(budget)
        policy = _dl.reconnect_policy()
        attempt = 0
        while not self._shutdown.is_set():
            if deadline.expired:
                _dl.count_deadline_exceeded("reconnect")
                print(
                    f"ray_tpu worker {self.worker_id.hex()[:8]}: head did "
                    f"not return within {budget:.0f}s "
                    "(head_reconnect_deadline_s); exiting",
                    file=sys.stderr, flush=True,
                )
                self._exit_with_drain(0)
            try:
                if self.client._try_reconnect():
                    with self._reconnect_guard:
                        self._reconnecting = False
                    return
            except Exception:
                pass
            if self.client.reconnect_refused is not None:
                # The head refused to adopt this identity (stale actor
                # incarnation, dead actor): this process's state is
                # unwanted — exit now, cleanly.
                print(
                    f"ray_tpu worker {self.worker_id.hex()[:8]}: head "
                    f"refused re-register "
                    f"({self.client.reconnect_refused}); exiting",
                    file=sys.stderr, flush=True,
                )
                self._exit_with_drain(0)
            attempt += 1
            _dl.count_retry("reconnect")
            policy.sleep(attempt, deadline)
        # Shutdown won the race: the run loop owns the exit path.

    def _exit_with_drain(self, code: int):
        """Terminal exit through the clean-shutdown drain: ship the log
        tees' trailing partial lines and the final metrics window, then
        _exit.  Never raises; never returns."""
        try:
            for stream in (sys.stdout, sys.stderr):
                if isinstance(stream, _LogTee):
                    stream.flush_residual()
            # Trailing spans (the final task's execution span lands in the
            # ring AFTER its task_done) must not die with the process.
            from ..util import gangrec as _gangrec
            from ..util import steprec as _steprec
            from ..util import tracing as _tracing

            _tracing.flush_spans(self.client)
            # Flight recorders: final step/round batches + forced black-box
            # dumps (the sidecars next to the log file are what post-mortem
            # tools read when the head never saw these records).
            _steprec.flush_steps(self.client)
            _steprec.dump_black_box(force=True)
            _gangrec.flush_rounds(self.client)
            _gangrec.dump_black_box(force=True)
            self.client._flush_submit_batch()
            from ray_tpu.util.metrics import _final_flush

            _final_flush()
        except BaseException:  # noqa: BLE001 — exiting regardless
            pass
        os._exit(code)

    # ---------------------------------------------------------- introspection

    def _on_stack_dump(self, body):
        """Collect every thread's Python stack and reply to the head.  Runs
        on the rpc loop thread: the executing task keeps running untouched
        (sys._current_frames is a snapshot, no signal, no interruption)."""
        try:
            names = {t.ident: t.name for t in threading.enumerate()}
            tasks_by_ident = {
                ident: tid for tid, ident in self.running_threads.items()
            }
            parts = []
            for ident, frame in sorted(sys._current_frames().items()):
                tid = tasks_by_ident.get(ident)
                note = f" [running task {tid.hex()[:16]}]" if tid else ""
                parts.append(
                    f"Thread {names.get(ident, '?')} (ident={ident}){note}:\n"
                    + "".join(traceback.format_stack(frame))
                )
            dump = "\n".join(parts)
            n_threads = len(parts)
        except Exception:
            dump = "stack collection failed:\n" + traceback.format_exc()
            n_threads = 0
        try:
            self.client.rpc.call_async("stack_dump_reply", {
                "token": body.get("token", 0),
                "pid": os.getpid(),
                "threads": n_threads,
                "dump": dump,
            })
        except Exception:
            pass

    def _on_profile(self, body):
        """On-demand profiler capture (head push, stack_dump-shaped token
        round trip): run util.profiling.device_trace around the live
        process for N seconds, then reply with the TensorBoard trace dir.
        The capture sleeps, so it MUST leave the rpc loop thread — a
        second concurrent request fails typed (ProfilerBusyError) rather
        than wedging the first."""
        def capture():
            token = body.get("token", 0)
            seconds = float(body.get("seconds", 3.0))
            logdir = body.get("logdir") or os.path.join(
                "/tmp/ray_tpu_profiles",
                f"worker-{self.worker_id.hex()[:8]}-{os.getpid()}")
            reply: Dict[str, Any] = {"token": token, "pid": os.getpid()}
            try:
                from ..util import profiling as _profiling

                with _profiling.device_trace(logdir):
                    time.sleep(max(0.05, seconds))
                reply["logdir"] = logdir
                try:
                    from ray_tpu.util.metrics import get_counter

                    get_counter(
                        "ray_tpu_profile_captures_total",
                        "completed on-demand device-trace captures",
                    ).inc()
                except Exception:
                    pass
            except Exception as e:
                reply["error"] = f"{type(e).__name__}: {e}"
            try:
                self.client.rpc.call_async("profile_reply", reply)
            except Exception:
                pass

        threading.Thread(target=capture, daemon=True,
                         name="profile-capture").start()

    def _devmem_loop(self):
        """Periodic device-memory report (util/devmem snapshot → head),
        joined into node snapshots and served by ``list_state("devmem")``
        / ``ray_tpu top``.  Headless windows just skip reports (the
        snapshot is cheap to retake; stale ones aren't worth replaying)."""
        from ..util import devmem as _devmem

        while not self._shutdown.is_set():
            interval = max(1.0, get_config().metrics_flush_interval_s)
            self._shutdown.wait(interval)
            if self._shutdown.is_set() or self.client.rpc.closed:
                continue
            try:
                snap = _devmem.maybe_snapshot()
                if snap is not None:
                    self.client.call_bg(
                        "devmem_report",
                        {"pid": os.getpid(), "devmem": snap})
            except Exception:
                pass

    # ------------------------------------------------------------ cancellation

    def _on_cancel(self, body):
        task_id = body["task_id"]
        self.cancelled.add(task_id)
        if body.get("force"):
            os._exit(1)
        ident = self.running_threads.get(task_id)
        if ident is not None:
            # Raise TaskCancelledError inside the executing thread (same
            # mechanism as the reference's cancellation handler in
            # _raylet.pyx execute_task_with_cancellation_handler).
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(ident),
                ctypes.py_object(exceptions.TaskCancelledError),
            )

    # ------------------------------------------------------------------- loop

    def run(self):
        while not self._shutdown.is_set():
            try:
                spec = self.task_queue.get(timeout=0.1)
            except queue.Empty:
                # Idle: completed-task reports must not sit in the batch
                # (their callers block until the head processes them).
                # Spans flush first so a finished task's execution span
                # rides the same coalesced head RPC as its task_done.
                from ..util import tracing as _tracing

                _tracing.flush_spans(self.client)
                self.client._flush_submit_batch()
                continue
            is_method = bool(spec.get("method_name"))
            fn = getattr(self.actor_instance, spec["method_name"], None) \
                if is_method and self.actor_instance is not None else None
            is_async = fn is not None and inspect.iscoroutinefunction(
                fn.__func__ if inspect.ismethod(fn) else fn
            )
            if is_method and not is_async:
                group = spec.get("concurrency_group") \
                    or self.method_groups.get(spec["method_name"])
                gpool = self.group_pools.get(group) if group else None
                if group and gpool is None:
                    self._finish_err(spec, ValueError(
                        f"unknown concurrency group {group!r}"))
                elif gpool is not None:
                    gpool.submit(self._execute, spec)
                elif self.pool is not None:
                    self.pool.submit(self._execute, spec)
                else:
                    self._execute(spec)
            else:
                # Async methods dispatch to the actor loop from here without
                # blocking, preserving queue order for sync methods.
                self._execute(spec)
        # Clean shutdown: os._exit skips atexit, so drain the log tees'
        # trailing partial lines and ship the final metrics window (incl.
        # the logs-dropped counter) explicitly.
        self._exit_with_drain(0)


def main():
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)  # kill -USR1 <pid> dumps all stacks
    worker = Worker()
    worker.run()


if __name__ == "__main__":
    sys.exit(main())
