"""Standalone head daemon: the control plane in its OWN process.

Role-equivalent to the reference's `ray start --head` GCS server process
(reference: src/ray/gcs/gcs_server/gcs_server_main.cc): drivers attach over
RT_ADDRESS instead of hosting the head in-process, so the head can crash —
and be restarted — independently of every workload.  Three things make a
restart survivable (the whole point of running the head this way):

- **fixed port** (``RT_HEAD_PORT``): headless nodes, workers, and drivers
  redial the address they already have;
- **stable session** (``RT_HEAD_SESSION``): the store namespace survives,
  so pre-crash shm segments stay addressable after resync;
- **stable local node id** (``RT_NODE_ID``): object locations recorded
  before the crash keep resolving to "the head's node" after it.

Pair with ``head_state_path`` (``RT_HEAD_STATE_PATH``) for the durable
tables and the restart becomes a bounded pause instead of an outage — see
``cluster_utils.ExternalHead`` for the supervised spawn/kill/restart
wrapper the chaos harness uses.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import uuid

from .config import Config, set_config
from .head import Head
from .ids import NodeID
from .rpc import ServerThread


def main() -> int:
    cfg = Config().apply_env_overrides()
    set_config(cfg)
    session = os.environ.get("RT_HEAD_SESSION") or uuid.uuid4().hex[:12]
    host = os.environ.get("RT_NODE_HOST", "127.0.0.1")
    port = int(os.environ.get("RT_HEAD_PORT", "0"))

    head = Head(cfg, session, host=host)
    head.server.port = port  # 0 = ephemeral; fixed for restartable heads
    server_thread = ServerThread(head.server)
    port = server_thread.start()
    head.port = port

    resources = json.loads(os.environ.get("RT_NODE_RESOURCES", "{}"))
    if "CPU" not in resources:
        resources["CPU"] = float(os.cpu_count() or 2)
    resources.setdefault("memory", float(2**33))
    num_workers = int(
        os.environ.get("RT_NODE_NUM_WORKERS", str(int(resources["CPU"])))
    )
    node_id = (
        NodeID(bytes.fromhex(os.environ["RT_NODE_ID"]))
        if os.environ.get("RT_NODE_ID") else None
    )

    async def _boot():
        head.add_local_node(resources, num_workers, node_id=node_id)
        await head.restore_state()
        await head.start_periodic()

    server_thread.run_coro(_boot()).result(timeout=60)

    addr = f"{host}:{port}"
    try:
        os.makedirs("/tmp/ray_tpu", exist_ok=True)
        with open("/tmp/ray_tpu/latest_address", "w") as f:
            f.write(addr)
    except OSError:
        pass
    # The supervisor (ExternalHead) waits for this line.
    print(f"RAY_TPU_HEAD_READY {addr} session={session}", flush=True)

    stop = threading.Event()

    def _on_term(*_):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    while not stop.is_set():
        time.sleep(0.2)
    try:
        server_thread.run_coro(head.stop()).result(timeout=10)
    except Exception:
        pass
    server_thread.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
