"""Head-side telemetry: built-in ``ray_tpu_*`` instruments and a bounded
time-series history of every cluster metric.

Role-equivalent to the reference's stats plane (reference:
src/ray/stats/metric_defs.cc — the built-in ray_* metric set; the dashboard
reads time series from the metrics agents via Prometheus).  Re-designed for
this framework's centralized head: the head already receives every
process's metric snapshots (``metrics_report``), so it *is* the natural
time-series store — a bounded, downsampled ring per (metric, tags) series,
served by ``list_state(kind="metrics_history")`` and the dashboard's
``/api/metrics/history`` endpoint, with sparkline panels in the HTML UI.

The head's own instruments (scheduler latency/queue depth, object-store
pressure, task durations) are plain ``util.metrics`` instruments created
with ``register=False``: they never ride the RPC flusher (the head would
be reporting to itself) — ``Head.metrics_rows()`` merges their snapshots
into the cluster aggregate directly.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..util.metrics import Counter, Gauge, Histogram


class MetricsHistory:
    """Bounded, downsampled ring per (metric name, tags) series.

    Appends are throttled to one sample per ``min_interval_s`` per series
    (the downsampling: a 2 s flusher cadence across 100 workers would
    otherwise burn the ring on near-duplicate timestamps), the ring holds
    ``max_samples`` points, and at most ``max_series`` distinct series are
    retained (tag-cardinality explosions drop new series, never grow
    memory)."""

    def __init__(self, max_samples: int = 360,
                 min_interval_s: float = 1.0, max_series: int = 1024):
        self.max_samples = max(2, int(max_samples))
        self.min_interval_s = float(min_interval_s)
        self.max_series = max(1, int(max_series))
        self._series: Dict[Tuple, dict] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(row: dict) -> Tuple:
        return (row["name"], tuple(sorted((row.get("tags") or {}).items())))

    def record(self, rows: List[dict], ts: Optional[float] = None) -> None:
        """Append one sample per series from aggregated metric rows.
        Histogram rows record their cumulative count (rate-of-change over
        the ring is the observation rate).

        Points are ``[ts, mean, min, max]``: samples arriving inside a
        series' ``min_interval_s`` bucket fold into the open point's
        running mean and min/max instead of being dropped — burn-rate and
        anomaly consumers need the extremes the mean would average away,
        and sparkline consumers keep reading indices 0/1 unchanged."""
        now = ts if ts is not None else time.time()
        with self._lock:
            for row in rows:
                value = row.get("value")
                if not isinstance(value, (int, float)):
                    continue
                key = self._key(row)
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self.max_series \
                            and not self._evict_stale(now):
                        continue  # cap reached, nothing stale to drop
                    s = self._series[key] = {
                        "name": row["name"],
                        "tags": dict(row.get("tags") or {}),
                        "kind": row.get("kind", "gauge"),
                        "points": deque(maxlen=self.max_samples),
                        "last_ts": 0.0,
                        "bucket_n": 0,
                    }
                v = float(value)
                if now - s["last_ts"] < self.min_interval_s and s["points"]:
                    p = s["points"][-1]
                    s["bucket_n"] += 1
                    p[1] += (v - p[1]) / s["bucket_n"]
                    p[2] = min(p[2], v)
                    p[3] = max(p[3], v)
                    continue
                s["last_ts"] = now
                s["bucket_n"] = 1
                s["points"].append([now, v, v, v])

    def _evict_stale(self, now: float) -> bool:
        """Make room at the series cap by dropping the longest-idle series,
        but only if it is genuinely dead (no sample for the stale window) —
        tag churn (per-pid replica gauges, per-rank train gauges) must not
        permanently crowd out freshly started live series, while an active
        series must never lose its ring to a newcomer."""
        stale_after = max(60.0, 30.0 * self.min_interval_s)
        oldest_key = min(self._series, key=lambda k: self._series[k]["last_ts"])
        if now - self._series[oldest_key]["last_ts"] < stale_after:
            return False
        del self._series[oldest_key]
        return True

    def snapshot(self, name_prefix: str = "") -> List[dict]:
        with self._lock:
            return [
                {"name": s["name"], "tags": s["tags"], "kind": s["kind"],
                 "points": [list(p) for p in s["points"]]}
                for s in self._series.values()
                if s["name"].startswith(name_prefix)
            ]


class HeadMetrics:
    """The head's built-in instrument set (all ``register=False``: snapshots
    are merged into the cluster aggregate by ``Head.metrics_rows()``)."""

    #: boundaries tuned for control-plane latencies (seconds).
    _LATENCY_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)

    def __init__(self):
        self.submit_to_start = Histogram(
            "ray_tpu_scheduler_submit_to_start_seconds",
            "Latency from task submission to dispatch on a worker",
            boundaries=self._LATENCY_BOUNDS, register=False)
        self.queue_depth = Gauge(
            "ray_tpu_scheduler_queue_depth",
            "Tasks queued or parked awaiting dispatch", register=False)
        self.tasks_dispatched = Counter(
            "ray_tpu_scheduler_tasks_dispatched_total",
            "Tasks dispatched to workers", register=False)
        self.task_duration = Histogram(
            "ray_tpu_task_duration_seconds",
            "Execution-span durations of traced tasks",
            boundaries=self._LATENCY_BOUNDS, register=False)
        self.store_used = Gauge(
            "ray_tpu_object_store_used_bytes",
            "Shared-memory object store bytes in use across cluster nodes",
            register=False)
        self.store_capacity = Gauge(
            "ray_tpu_object_store_capacity_bytes",
            "Total shared-memory object store capacity across cluster nodes",
            register=False)
        self.store_stored = Gauge(
            "ray_tpu_object_store_bytes_stored_total",
            "Cumulative bytes written into cluster object stores",
            register=False)
        self.store_transferred = Gauge(
            "ray_tpu_object_store_bytes_transferred_total",
            "Cumulative bytes served to cross-node object pulls",
            register=False)
        self.store_hit_rate = Gauge(
            "ray_tpu_object_store_hit_rate",
            "Fraction of store reads served from shm (vs miss/spill), cluster-wide",
            register=False)
        self.lease_revocations = Counter(
            "ray_tpu_lease_revocations_total",
            "Task-lease revocations (TTL expiry, node drain, worker death, "
            "or scheduler preemption of idle-held slots)",
            tag_keys=("reason",), register=False)
        # -- head fault tolerance (headless mode + field-state resync) --------
        self.head_restarts = Counter(
            "ray_tpu_head_restarts_total",
            "Head restarts observed (durable snapshot restored at boot)",
            register=False)
        self.headless_seconds = Gauge(
            "ray_tpu_headless_seconds",
            "Cumulative seconds each node daemon has run without a head "
            "connection (reconnect loop active, field ops degraded)",
            tag_keys=("node",), register=False)
        self.resync_reports = Counter(
            "ray_tpu_resync_reports_total",
            "Field-state resync reports adopted at re-register (nodes "
            "replaying store manifests, workers re-binding live actors)",
            tag_keys=("kind",), register=False)
        # -- health / incident plane (util/health.py, wired in the head) ------
        self.incidents_opened = Counter(
            "ray_tpu_incidents_opened_total",
            "Incidents opened by the health detector pass",
            tag_keys=("kind",), register=False)
        self.incidents_resolved = Counter(
            "ray_tpu_incidents_resolved_total",
            "Incidents resolved after their detector went quiet",
            register=False)
        self.loop_lag = Gauge(
            "ray_tpu_head_loop_lag_seconds",
            "Head event-loop scheduling lag measured by the periodic-tick "
            "probe (how late the tick woke up)", register=False)
        self.rpc_handler = Histogram(
            "ray_tpu_head_rpc_handler_seconds",
            "Head RPC handler wall time per method",
            boundaries=self._LATENCY_BOUNDS, tag_keys=("method",),
            register=False)
        # -- gang training observability (h_gang_round_batch join) ------------
        self.gang_round_skew = Histogram(
            "ray_tpu_gang_round_skew_seconds",
            "Per-round gang skew (straggler's lead over the median rank) "
            "observed when a round joins across all ranks",
            boundaries=self._LATENCY_BOUNDS, register=False)
        self._all = [
            self.submit_to_start, self.queue_depth, self.tasks_dispatched,
            self.task_duration, self.store_used, self.store_capacity,
            self.store_stored, self.store_transferred, self.store_hit_rate,
            self.lease_revocations,
            self.head_restarts, self.headless_seconds, self.resync_reports,
            self.incidents_opened, self.incidents_resolved, self.loop_lag,
            self.rpc_handler, self.gang_round_skew,
        ]

    def sample_store(self, stats: dict) -> None:
        """Refresh object-store gauges from an ObjectStore.stats() dict."""
        self.store_used.set(float(stats.get("used_bytes", 0)))
        self.store_capacity.set(float(stats.get("capacity_bytes", 0)))
        self.store_stored.set(float(stats.get("bytes_stored_total", 0)))
        self.store_transferred.set(
            float(stats.get("bytes_transferred_total", 0)))
        hits = stats.get("gets_hit", 0)
        misses = stats.get("gets_miss", 0)
        if hits + misses > 0:
            self.store_hit_rate.set(hits / (hits + misses))

    def rows(self) -> List[dict]:
        out: List[dict] = []
        for m in self._all:
            out.extend(m._snapshot())
        return out
