"""Control plane: object directory, task scheduling/dispatch, actor lifecycle,
placement groups, KV store, pubsub, worker-pool management.

Role-equivalent to the reference's GCS server + raylet combination
(reference: src/ray/gcs/gcs_server/gcs_server.h:78 — actor/node/job/PG/KV/
pubsub services; src/ray/raylet/node_manager.h:119 — leasing + dispatch;
src/ray/core_worker/task_manager.h:208 — retries + lineage).  Design choice
vs the reference: ownership of the object directory and the task table is
centralized in this process rather than distributed across core workers —
a deliberately simpler protocol (single writer, no borrowing dance) that a
TPU cluster's scale profile (hundreds of hosts, gang-scheduled SPMD jobs)
tolerates well; scale-out path is sharding the table, not distributing
ownership.

All state is owned by one asyncio loop — handlers never block.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Set

from . import serialization
from ..exceptions import ActorDiedError, TaskCancelledError, WorkerCrashedError
from .config import Config
from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .rpc import Connection, RpcServer
from .scheduler import ClusterScheduler, SchedulingStrategy
from ..devtools.locks import guarded, make_lock

logger = logging.getLogger(__name__)

# Worker / actor / task states (subset of the reference FSMs:
# gcs_actor_manager.h actor FSM, worker_pool.h worker states).
STARTING, IDLE, LEASED, ACTOR, DEAD = "starting", "idle", "leased", "actor", "dead"
# A worker that ran a TPU-chip-granted task: told to exit, never re-picked
# (the process keeps the chips mapped until it dies).
RETIRING = "retiring"
# BLOCKED: leased worker parked in a nested get/wait; its task's resources
# are released so the pool can run other work (see h_task_blocked).
BLOCKED = "blocked"
# DIRECT: worker leased out to a client for peer-to-peer task submission —
# the client pushes specs straight to the worker's peer server and the head
# never sees the per-call traffic (reference: raylet worker leasing +
# core-worker direct task push).  Excluded from head dispatch until the
# lease returns.
DIRECT = "direct"
PENDING, RUNNING, FINISHED, FAILED = "PENDING", "RUNNING", "FINISHED", "FAILED"


def _strategy_from_wire(d: Optional[dict]) -> SchedulingStrategy:
    if not d:
        return SchedulingStrategy.default()
    return SchedulingStrategy(
        kind=d.get("kind", "default"),
        node_id=NodeID(d["node_id"]) if d.get("node_id") else None,
        soft=d.get("soft", False),
        pg_id=PlacementGroupID(d["pg_id"]) if d.get("pg_id") else None,
        bundle_index=d.get("bundle_index", -1),
    )


class WorkerState:
    def __init__(self, worker_id: WorkerID, node_id: NodeID, conn: Connection, pid: int):
        self.worker_id = worker_id
        self.node_id = node_id
        self.conn = conn
        self.pid = pid
        # Workers start in STARTING and flip to IDLE on the worker_ready
        # handshake — dispatching before the worker has installed its push
        # handlers would drop the task push.
        self.state = STARTING
        self.inflight: Set[TaskID] = set()  # tasks currently on this worker
        self.actor_id: Optional[ActorID] = None
        self.last_seen = time.monotonic()  # last dispatch/completion activity
        self.last_ack = time.monotonic()   # last health-check ack
        # TPU chip IDs this worker process has been granted.  jax/libtpu
        # keep the devices mapped until process exit, so the IDs return to
        # the node pool only at worker death (see _handle_worker_death).
        self.tpu_chips: List[int] = []
        # True once any task ran here: a used worker may have initialized
        # jax on CPU, so chip grants (which flip JAX_PLATFORMS before the
        # first jax import) only go to fresh processes.
        self.used = False
        # Address of the worker's peer RPC server (direct actor calls and
        # leased task submission dial this).  Registered at worker_ready.
        self.peer_addr: str = ""


_task_seq = 0


class TaskRecord:
    def __init__(self, spec: dict):
        global _task_seq
        self.spec = spec
        self.task_id = TaskID(spec["task_id"])
        self.state = PENDING
        # Wall-clock submission time: feeds the built-in submit→start
        # latency histogram at dispatch.
        self.submit_time = time.time()
        self.pending_deps: Set[ObjectID] = set()
        self.worker_id: Optional[WorkerID] = None
        self.node_id: Optional[NodeID] = None
        self.retries_left = spec.get("max_retries", 0)
        self.start_time = 0.0
        self.end_time = 0.0
        self.error: Optional[str] = None
        # Submission order (used to restore FIFO when in-flight actor tasks
        # are requeued after a worker death) and blocked-in-get flag.
        _task_seq += 1
        self.seq = _task_seq
        self.blocked = False
        # Sticky placement: once the scheduler picks a node the task commits
        # to it (resources held) and parks until a worker there frees up
        # (reference: spread_scheduling_policy.h — the lease stays on the
        # chosen raylet while its worker pool spins up a worker).
        self.parked_node: Optional[NodeID] = None
        self.park_time = 0.0
        # Concrete TPU chip IDs granted at dispatch (tasks requesting
        # {"TPU": n}); freed back to the node's pool with the resources.
        self.tpu_chips: Optional[List[int]] = None

    @property
    def is_actor_task(self) -> bool:
        return bool(self.spec.get("actor_id")) and not self.spec.get(
            "is_actor_creation"
        )

    @property
    def resources(self) -> Dict[str, float]:
        # api.py always sends explicit resources; {} (e.g. zero-CPU actors)
        # must stay empty, not fall back to 1 CPU.
        res = self.spec.get("resources")
        return dict(res) if res is not None else {"CPU": 1.0}

    @property
    def strategy(self) -> SchedulingStrategy:
        return _strategy_from_wire(self.spec.get("strategy"))

    def shape_key(self) -> tuple:
        """Placement-equivalence key: tasks with equal keys place (or fail to
        place) identically in a given cluster state — the analog of the
        reference's SchedulingClass (src/ray/common/task/task_spec.h).
        Memoized: the dispatch loop consults it on every queue scan, and a
        large burst is rescanned once per completion — recomputing the
        sorted tuples dominated scheduling CPU (observed: 1M recomputes for
        a 2k-task burst)."""
        cached = self.__dict__.get("_shape_key")
        if cached is None:
            res = self.spec.get("resources")
            strat = self.spec.get("strategy")
            cached = self._shape_key = (
                tuple(sorted(res.items())) if res else None,
                tuple(sorted(
                    (k, v if not isinstance(v, (bytes, bytearray))
                     else bytes(v))
                    for k, v in strat.items()
                )) if strat else None,
            )
        return cached


class ActorRecord:
    def __init__(self, actor_id: ActorID, spec: dict):
        self.actor_id = actor_id
        self.spec = spec
        self.state = "PENDING"  # PENDING|ALIVE|RESTARTING|DEAD
        self.worker_id: Optional[WorkerID] = None
        self.node_id: Optional[NodeID] = None
        self.restarts_left = spec.get("max_restarts", 0)
        self.name = spec.get("name") or ""
        # Tasks queued while the actor is pending/restarting.
        self.pending_tasks: deque = deque()
        self.num_executed = 0
        self.death_cause: Optional[str] = None


class ObjectRecord:
    __slots__ = (
        "object_id", "size", "inline", "locations", "error",
        "ref_count", "task_id", "sealed", "spilled",
    )

    def __init__(self, object_id: ObjectID):
        self.object_id = object_id
        self.size = 0
        self.inline: Optional[bytes] = None
        self.locations: Set[NodeID] = set()
        self.error: Optional[bytes] = None  # serialized exception
        self.ref_count = 1  # creator's reference
        self.task_id: Optional[TaskID] = None
        self.sealed = False
        self.spilled = False


@guarded
class Head:
    """The control-plane server."""

    # Spawn bookkeeping is mutated off-loop (executor spawn threads) while
    # the loop prunes/kills: rtlint RT007 verifies these statically and
    # RT_DEBUG_LOCKS=2 asserts them at runtime (devtools.locks).
    _RT_GUARDED_BY = {
        "worker_pids": "_pids_lock",
        "worker_procs": "_pids_lock",
        "_zygote": "_zygote_mutex",
    }
    _RT_UNGUARDED = {
        "_state_dirty": "monotonic re-arm: the loop clears it before the "
                        "off-loop dump and ONLY the failed dump sets it "
                        "back True — a racing loop-side _mark_dirty stores "
                        "the same value, and a lost False just means one "
                        "redundant snapshot next tick",
    }

    def __init__(self, config: Config, session: str, host: str = "127.0.0.1"):
        self.config = config
        self.session = session
        self.server = RpcServer(host=host, name="head-server")
        self.scheduler = ClusterScheduler(config.scheduler_spread_threshold)
        self.host = host
        self.port = 0

        # Local node's store daemon: accounting, eviction, spill, cleanup.
        from .object_store import ObjectStore

        self.store = ObjectStore(
            session, config.object_store_memory, config.spill_dir
        )
        self.kv: Dict[str, bytes] = {}
        self.workers: Dict[WorkerID, WorkerState] = {}
        self.conn_to_worker: Dict[int, WorkerID] = {}
        self.tasks: Dict[TaskID, TaskRecord] = {}
        self.tasks_waiting_on: Dict[ObjectID, Set[TaskID]] = {}
        self.finished_tasks: deque = deque(maxlen=10_000)  # for the state API
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self.objects: Dict[ObjectID, ObjectRecord] = {}
        self.object_waiters: Dict[ObjectID, List[asyncio.Event]] = {}
        self.queued_tasks: deque = deque()  # TaskRecords ready to schedule
        # Shape histogram of queued_tasks: lets a dispatch pass stop as
        # soon as every shape still in the queue has already failed to
        # place — a homogeneous 10k-task burst costs O(1) per pass instead
        # of an O(n) rescan (reference: cluster_task_manager.h groups by
        # SchedulingClass).
        self.queue_shapes: Dict[tuple, int] = {}
        # Tasks committed to a node (resources held), awaiting an idle worker.
        self.node_parked: Dict[NodeID, deque] = {}
        # PGs with bundles lost to node death, awaiting re-placement.
        self.pgs_needing_bundles: Set[PlacementGroupID] = set()
        self.stream_items: Dict[tuple, dict] = {}  # (task_id, idx) -> item info
        self.stream_waiters: Dict[tuple, List[asyncio.Event]] = {}
        self.stream_done: Dict[TaskID, int] = {}  # total item count when finished
        self.subs: Dict[str, Set[int]] = {}  # topic -> conn ids
        self.node_sessions: Dict[NodeID, str] = {}  # store session per node
        self.node_worker_caps: Dict[NodeID, int] = {}
        self.node_worker_counts: Dict[NodeID, int] = {}
        self.local_node_id: Optional[NodeID] = None
        self.worker_procs: List[subprocess.Popen] = []
        self.worker_pids: List[int] = []  # zygote-forked (init reaps them)
        self._zygote = None
        self._zygote_mutex = make_lock("head.zygote")
        # Guards worker_pids/worker_procs only (list ops, microseconds):
        # spawns mutate them from executor threads while the loop prunes
        # exited pids — never hold this across the zygote handshake.
        self._pids_lock = make_lock("head.worker_pids")
        self.node_daemons: Dict[NodeID, Connection] = {}
        # Object-plane server address per node (chunked pull endpoint).
        self.node_object_addrs: Dict[NodeID, str] = {}
        self.node_bulk_addrs: Dict[NodeID, str] = {}
        self.node_last_ack: Dict[NodeID, float] = {}
        self.task_events: deque = deque(maxlen=config.task_events_buffer_size)
        self._events_since_persist = 0
        # -- debugging plane --------------------------------------------------
        # Cluster-wide log index: proc_id (worker/node hex) -> registered log
        # file + liveness.  Entries of EXITED processes are retained (bounded,
        # dead-oldest evicted first) so `get_log` works for crash post-mortems
        # (reference: the GCS worker table keeps dead workers for `ray logs`).
        self.log_index: "OrderedDict[str, dict]" = OrderedDict()
        # Per-task lifecycle histories: task hex -> record with a bounded
        # transition list + failure traceback, queryable via
        # list_state(kind="task_events") (reference: gcs_task_manager.h —
        # task events survive the worker because the HEAD holds them).
        self.task_history: "OrderedDict[str, dict]" = OrderedDict()
        # In-flight stack-dump round-trips: token -> future resolved by the
        # worker's stack_dump_reply.
        self._stack_waiters: Dict[int, asyncio.Future] = {}
        self._stack_token = 0
        # In-flight profile round-trips (same token discipline; resolved
        # by profile_reply after the worker's N-second capture).
        self._profile_waiters: Dict[int, asyncio.Future] = {}
        # Flight-recorder plane: per-engine bounded step-record rings fed
        # by h_engine_step_batch; list_state("engine_steps") and
        # `ray_tpu top` read them (engine id -> deque of records,
        # oldest-engine evicted when the table itself fills).
        self.engine_steps: "OrderedDict[str, deque]" = OrderedDict()
        # Gang training observability: per-gang join state fed by
        # h_gang_round_batch — rounds awaiting a record from every rank
        # ("pending"), the bounded ring of joined skew profiles, and the
        # latest raw record per rank.  Oldest-idle gang evicted when the
        # table hits gang_rounds_max_gangs; read by
        # list_state("gang_rounds"), `ray_tpu gang`, and the gang health
        # detectors.
        self.gang_rounds: "OrderedDict[str, dict]" = OrderedDict()
        # Device-memory accounting: latest util/devmem snapshot per
        # reporting worker pid, identity-joined at report time.
        self.devmem_by_pid: Dict[int, dict] = {}
        # Named actors that could NOT be restored after a head restart
        # (constructor args lived in the dead session's object store):
        # name -> human-readable reason, surfaced by get_actor(name)
        # (reference: GCS actor table entries keep a death cause).
        self.named_tombstones: Dict[str, str] = {}
        # Named actors restored from the snapshot but NOT yet re-created:
        # replay waits out head_resync_grace_s so a surviving worker's
        # field report can adopt the LIVE instance instead of racing a
        # fresh duplicate (name -> create_actor body); the periodic loop
        # replays the leftovers after the deadline.
        self._restore_named_pending: Dict[str, dict] = {}
        self._restore_named_deadline = 0.0
        # Resync race absorbers (head restart): until this deadline, actor
        # submissions for unknown actors PARK instead of failing — a
        # reconnected driver's replayed batch may legitimately precede the
        # hosting worker's adoption report.  Drained on adoption/replay;
        # leftovers fail typed when the window closes.
        self._resync_grace_until = 0.0
        self._parked_unknown_actor_tasks: List[dict] = []
        self._spawn_pending: Dict[NodeID, int] = {}
        self._spawn_times: Dict[NodeID, deque] = {}
        # Placement groups waiting for resources to free up (reference:
        # gcs_placement_group_manager queues pending PGs).
        self.pending_pgs: "Dict[PlacementGroupID, dict]" = {}
        # Creation bodies of every live PG (reserved or pending) — the
        # durable PG table: detached ones are replayed on head restart
        # (reference: gcs_table_storage.h PlacementGroupTable).
        self.pg_bodies: "Dict[PlacementGroupID, dict]" = {}
        # Non-detached PGs are scoped to their creator's connection.
        self.pg_owner_conn: "Dict[PlacementGroupID, int]" = {}
        self._pending_frees: Dict[int, dict] = {}
        self._free_token = 0
        # Live task leases: lease_id -> {worker_id, node_id, conn_id,
        # resources, expires, revoke_deadline}.  A lease is the head's
        # record that a worker's execution slot (and its resources) belongs
        # to a client for direct submission (reference: raylet
        # LocalLeaseManager's leased-worker table).
        self.leases: Dict[bytes, dict] = {}
        self._last_lease_preempt = 0.0
        self.metrics_by_pid: Dict[int, list] = {}
        # Counters/histograms of departed processes (see _retire_metrics):
        # cluster totals must stay monotonic across worker churn.
        self._metrics_retired: Dict[tuple, dict] = {}
        # Per-pid retired contributions, so a RECONNECTED process (driver
        # reconnect path) that re-reports its cumulative counters doesn't
        # get double-counted against its own retired snapshot.
        self._retired_by_pid: Dict[int, list] = {}
        # Cumulative store counters of departed NODES, same invariant.
        self._store_retired: Dict[str, float] = {}
        self._state_dirty = True  # persist once at startup when configured
        # Lineage: finished task specs kept (args pinned) so lost objects can
        # be recomputed by re-running their creating task (reference:
        # object_recovery_manager.h:90, reference_count.h:75).
        self.lineage: "OrderedDict[TaskID, dict]" = OrderedDict()
        self.reconstruction_counts: Dict[TaskID, int] = {}
        self.pg_waiters: Dict[PlacementGroupID, List[asyncio.Event]] = {}
        self._proxy_uploads: Dict[ObjectID, Any] = {}
        # Last per-node resource view from each daemon (the resource-syncer
        # table — reference: ray_syncer.h:88; consumed by the state API and
        # dashboard).
        self.node_stats: Dict[NodeID, dict] = {}
        # Workers killed by the memory monitor: their tasks' failure message
        # names the cause (reference: worker_killing_policy*.h attributes
        # OOM kills in the task error).  Ordered so the bound evicts oldest.
        self._oom_kills: "OrderedDict[WorkerID, float]" = OrderedDict()
        # Per-node kill cooldown: remote stats refresh every ~2s while this
        # check runs every tick — without the cooldown one stale reading
        # would kill a worker per tick.
        self._last_oom_kill: Dict[NodeID, float] = {}
        self._periodic_task: Optional[asyncio.Task] = None
        self._tick_task: Optional[asyncio.Task] = None
        self._shutdown = False
        self._kick_scheduled = False
        self.job_start_time = time.time()
        # Built-in ray_tpu_* instruments + retained time-series history
        # (see core/telemetry.py).  The history is fed by the periodic loop
        # from the same aggregate `list_state(kind="metrics")` serves.
        from .telemetry import HeadMetrics, MetricsHistory

        self.builtin_metrics = HeadMetrics()
        self.metrics_history = MetricsHistory(
            max_samples=config.metrics_history_max_samples,
            min_interval_s=config.metrics_history_min_interval_s,
            max_series=config.metrics_history_max_series,
        )
        # Health / incident plane (util/health.py): the detector pass runs
        # on the telemetry sampling cadence over the SAME aggregated rows
        # the history ring retains; incidents live only here (head-volatile,
        # like the timeline ring).  Loop-lag is probed by _periodic_loop.
        from ..util.health import HealthEngine

        self.health = HealthEngine(
            window_s=config.health_window_s,
            resolve_after_s=config.health_resolve_after_s,
            max_incidents=config.health_max_incidents,
            params={
                "slo_goal": config.health_slo_goal,
                "burn_fast_s": config.health_slo_fast_window_s,
                "burn_slow_s": config.health_slo_slow_window_s,
            },
            on_open=self._on_incident_open,
            on_resolve=self._on_incident_resolve,
        )
        self._loop_lag_s = 0.0

        for name in [
            "register", "kv_put", "kv_get", "kv_del", "kv_keys",
            "submit_task", "create_actor", "submit_actor_task",
            "task_done", "stream_item", "metrics_report", "batch",
            "put_object", "put_object_batch", "proxy_put",
            "get_objects",
            "wait_objects", "free_objects", "object_free_ack",
            "add_object_ref", "reconstruct_object",
            "create_placement_group", "remove_placement_group",
            "kill_actor", "cancel_task", "get_actor_by_name", "list_named_actors",
            "worker_ready",
            "publish", "subscribe", "cluster_resources", "available_resources",
            "next_stream_item", "list_state", "object_sizes",
            "ping", "shutdown_cluster",
            "restore_object", "store_stats",
            "task_blocked", "task_unblocked", "health_ack", "pg_ready",
            "node_health_ack", "node_stats", "node_drain", "span_batch",
            "get_log", "stack_dump", "stack_dump_reply",
            "engine_step_batch", "gang_round_batch", "devmem_report",
            "profile", "profile_reply",
            "resolve_actor", "lease_request", "lease_return", "lease_renew",
            "direct_done",
        ]:
            self.server.register(
                name, self._timed(name,
                                  _validated(name, getattr(self, f"h_{name}")))
            )
        # The head serves chunked pulls for its own node's objects
        # (remote nodes serve theirs via their daemon's object-plane server).
        from .node_main import make_pull_handler

        self.server.register("pull_object", make_pull_handler(self.store))
        self.server.on_disconnect = self._on_disconnect

    # ------------------------------------------------------------------ utils

    def _event(self, kind: str, **kw):
        if self.config.enable_timeline:
            self.task_events.append({"ts": time.time(), "kind": kind, **kw})
            # Coarse durability cadence: the event log rides the snapshot,
            # but marking dirty per event would re-pickle the whole state
            # every tick under load.  Every 100th event is enough for a
            # "recent timeline survives restart" guarantee.
            self._events_since_persist += 1
            if self._events_since_persist >= 100:
                self._events_since_persist = 0
                self._mark_dirty()

    # -- debugging plane: log index + task lifecycle history ------------------

    def _log_register(self, proc_id: str, kind: str, node_id: NodeID,
                      pid: int, log_path: str):
        """Add (or refresh) a process's entry in the cluster log index."""
        cap = self.config.log_index_max_entries
        if cap <= 0:
            return
        self.log_index.pop(proc_id, None)
        self.log_index[proc_id] = {
            "proc_id": proc_id,
            "kind": kind,
            "node_id": node_id.hex(),
            "pid": pid or 0,
            "log_path": log_path or "",
            "alive": True,
            "actor_id": None,
            "start_time": time.time(),
            "end_time": None,
        }
        while len(self.log_index) > cap:
            victim = next(
                (p for p, e in self.log_index.items() if not e["alive"]), None
            )
            if victim is None:
                self.log_index.popitem(last=False)
            else:
                self.log_index.pop(victim)

    def _log_mark_dead(self, proc_id: str):
        entry = self.log_index.get(proc_id)
        if entry is not None and entry["alive"]:
            entry["alive"] = False
            entry["end_time"] = time.time()

    def _resolve_log_entry(self, query: str):
        """Match a log-index entry by worker/node id (exact or unique
        prefix), the actor an entry's worker hosts/hosted, or pid.
        Returns ``(entry, error)`` — an ambiguous prefix gets an explicit
        error, never a misleading not-found (nor an arbitrary match)."""
        if not query:
            return None, "empty process id"
        entry = self.log_index.get(query)
        if entry is not None:
            return entry, None
        matches = [
            e for pid, e in self.log_index.items()
            if pid.startswith(query)
            or (e["actor_id"] or "").startswith(query)
        ]
        if len(matches) == 1:
            return matches[0], None
        if len(matches) > 1:
            return None, (f"{query!r} is ambiguous: matches "
                          f"{len(matches)} processes — use a longer prefix "
                          "(see list_state(kind='logs'))")
        if query.isdigit():
            by_pid = [e for e in self.log_index.values()
                      if e["pid"] == int(query)]
            if len(by_pid) == 1:
                return by_pid[0], None
            if len(by_pid) > 1:
                return None, (f"pid {query} matches {len(by_pid)} "
                              "processes (recycled pid) — use the "
                              "worker/node id instead")
        return None, (f"no log registered for {query!r} "
                      "(see list_state(kind='logs') for known ids)")

    def _task_transition(self, task: "TaskRecord", state: str,
                         node: Optional[NodeID] = None,
                         error: Optional[str] = None,
                         traceback_text: Optional[str] = None):
        """Append one lifecycle transition to the task's retained history
        (the task-event store: SUBMITTED/SCHEDULED/RUNNING/RETRYING/
        FINISHED/FAILED with timestamps, placement, and the full traceback
        on failure — survives worker and node death by living here)."""
        cap = self.config.task_history_max_tasks
        if cap <= 0:
            return
        hexid = task.task_id.hex()
        rec = self.task_history.get(hexid)
        if rec is None:
            rec = self.task_history[hexid] = {
                "task_id": hexid,
                "name": task.spec.get("name", ""),
                "actor_id": (ActorID(task.spec["actor_id"]).hex()
                             if task.spec.get("actor_id") else None),
                "state": state,
                "node_id": None,
                "worker_id": None,
                "error": None,
                "traceback": None,
                "events": [],
            }
            while len(self.task_history) > cap:
                self.task_history.popitem(last=False)
        ev: Dict[str, Any] = {"state": state, "ts": time.time()}
        nid = node or task.node_id
        if nid is not None:
            rec["node_id"] = ev["node"] = nid.hex()
        if task.worker_id is not None:
            rec["worker_id"] = ev["worker"] = task.worker_id.hex()
        if error:
            rec["error"] = ev["error"] = error
        if traceback_text:
            rec["traceback"] = traceback_text
        rec["state"] = state
        events = rec["events"]
        events.append(ev)
        if len(events) > self.config.task_history_max_events:
            # Keep the SUBMITTED head; a retry loop sheds its oldest middle.
            del events[1]

    def _obj(self, oid: ObjectID) -> ObjectRecord:
        rec = self.objects.get(oid)
        if rec is None:
            rec = self.objects[oid] = ObjectRecord(oid)
        return rec

    def _notify_object_ready(self, oid: ObjectID):
        for ev in self.object_waiters.pop(oid, []):
            ev.set()
        # Unblock tasks waiting on this dependency (indexed, not scanned).
        drained_actors = set()
        for tid in self.tasks_waiting_on.pop(oid, ()):
            task = self.tasks.get(tid)
            if task is None or task.state != PENDING:
                continue
            task.pending_deps.discard(oid)
            if task.pending_deps:
                continue
            if task.is_actor_task:
                # Actor tasks stay in the actor's FIFO queue; a newly
                # dep-free head-of-queue can now drain.
                aid = ActorID(task.spec["actor_id"])
                if aid not in drained_actors:
                    drained_actors.add(aid)
                    actor = self.actors.get(aid)
                    if actor is not None and actor.state == "ALIVE":
                        asyncio.ensure_future(self._drain_actor_queue(actor))
            elif task not in self.queued_tasks:
                self._enqueue_task(task)
        self._kick()

    def _kick(self):
        """Schedule a dispatch pass on the loop.  Coalesced: a burst of
        submissions (the client pipelines them) triggers one pass, not one
        pass per task — each pass scans the whole queue, so per-call passes
        turn a k-task burst into O(k²) scheduler work."""
        if self._kick_scheduled:
            return
        self._kick_scheduled = True

        def run():
            self._kick_scheduled = False
            asyncio.ensure_future(self._dispatch_loop())

        asyncio.get_running_loop().call_soon(run)

    # -------------------------------------------------------------- lifecycle

    async def start(self) -> int:
        self.port = await self.server.start()
        await self.start_periodic()
        return self.port

    async def start_periodic(self):
        """Launch the housekeeping loop on the serving event loop (callers
        that start the RpcServer directly must invoke this themselves)."""
        if self._periodic_task is None:
            self._periodic_task = asyncio.ensure_future(self._periodic_loop())
            self._tick_task = asyncio.ensure_future(self._store_tick_loop())

    async def _store_tick_loop(self):
        """Move cooled freed segments into the warm pool promptly (the main
        periodic loop may run at a coarser health-check cadence)."""
        while not self._shutdown:
            await asyncio.sleep(0.25)
            try:
                self.store.tick()
                self._expire_pending_frees()
            except Exception:
                pass

    async def _periodic_loop(self):
        """Housekeeping: worker health probes, idle-worker reaping, spawn
        timeout reclamation, pending-PG retry (reference:
        gcs_health_check_manager.h, worker_pool.h idle killing)."""
        cfg = self.config
        period = max(0.1, min(cfg.health_check_period_s, 1.0))
        while not self._shutdown:
            try:
                _t_sleep = time.monotonic()
                await asyncio.sleep(period)
                now = time.monotonic()
                # Event-loop lag probe: how late did this tick wake up?
                # Sustained lag means every handler is queueing behind
                # something — the health plane's head-pressure detector
                # watches the windowed max of this gauge.
                self._loop_lag_s = max(0.0, now - _t_sleep - period)
                self.builtin_metrics.loop_lag.set(self._loop_lag_s)
                self.store.tick()  # cooled freed segments -> warm pool
                try:
                    self._sample_telemetry()
                except Exception:
                    pass
                try:
                    self.persist_state()
                except Exception:
                    pass
                # Deferred snapshot replay: named actors the resync grace
                # window left unclaimed get re-created now (field reports
                # that arrived in time adopted the live instances instead).
                if self._restore_named_pending \
                        and now >= self._restore_named_deadline:
                    pending = self._restore_named_pending
                    self._restore_named_pending = {}
                    for name, spec in pending.items():
                        try:
                            await self._replay_named_actor(name, spec)
                        except Exception:
                            pass
                    # Replayed actors unblock their parked submissions.
                    await self._drain_parked_unknown_actor_tasks()
                if self._parked_unknown_actor_tasks \
                        and now >= self._resync_grace_until:
                    # Window closed: whatever is still unknown fails typed.
                    await self._drain_parked_unknown_actor_tasks(force=True)
                # Prune exited zygote-forked workers (orphans reaped by
                # init) so shutdown never signals a recycled pid.
                with self._pids_lock:
                    pids = list(self.worker_pids)
                for pid in pids:
                    try:
                        os.kill(pid, 0)
                    except (ProcessLookupError, PermissionError):
                        with self._pids_lock:
                            if pid in self.worker_pids:
                                self.worker_pids.remove(pid)
                # Health probes: push to every worker; acks come back via
                # h_health_ack.  A wedged process keeps the TCP connection
                # open but its rpc loop stops acking.
                dead_after = cfg.health_check_period_s * cfg.health_check_failure_threshold
                for w in list(self.workers.values()):
                    if not w.conn.alive:
                        continue
                    try:
                        await w.conn.push("health_check", {})
                    except Exception:
                        continue
                    if now - w.last_ack > dead_after:
                        self._event("worker_health_timeout",
                                    worker=w.worker_id.hex())
                        if w.node_id == self.local_node_id:
                            try:
                                os.kill(w.pid, 9)
                            except (ProcessLookupError, PermissionError):
                                pass
                        else:
                            # A wedged (e.g. SIGSTOP'd) process can't run its
                            # connection-lost handler; its node daemon holds
                            # the Popen handle and delivers the SIGKILL.
                            daemon = self.node_daemons.get(w.node_id)
                            if daemon is not None:
                                try:
                                    await daemon.push(
                                        "kill_worker", {"pid": w.pid}
                                    )
                                except Exception:
                                    pass
                        w.conn.writer.close()  # triggers _on_disconnect
                # Node-daemon liveness (reference: GcsHealthCheckManager
                # probes every raylet).
                for node_id, conn in list(self.node_daemons.items()):
                    try:
                        await conn.push("health_check", {})
                    except Exception:
                        continue
                    last = self.node_last_ack.get(node_id, now)
                    if now - last > dead_after:
                        self._event("node_health_timeout", node=node_id.hex())
                        conn.writer.close()  # triggers node-death handling
                # Idle reaping: task-pool workers idle beyond the window exit
                # cleanly; demand respawns them.  Fresh (never-used) workers
                # are exempt up to the prestart spare budget — they ARE the
                # spare pool.
                idle_t = cfg.idle_worker_killing_time_s
                spares = cfg.prestart_spare_workers
                fresh_kept: Dict[NodeID, int] = {}
                for w in list(self.workers.values()):
                    if not (w.state == IDLE and w.conn.alive
                            and now - w.last_seen > idle_t):
                        continue
                    if not w.used and spares > 0:
                        kept = fresh_kept.get(w.node_id, 0)
                        if kept < spares:
                            fresh_kept[w.node_id] = kept + 1
                            continue
                    try:
                        await w.conn.push("shutdown", {})
                    except Exception:
                        pass
                # Prestart: keep the spare pool of fresh forked workers
                # filled so actor creations skip the fork+boot+register
                # latency (reference: worker_pool.h prestart).
                if spares > 0:
                    for node_id, cap in self.node_worker_caps.items():
                        if cap <= 0:
                            continue
                        # Never prestart for a node whose daemon is gone
                        # (caps outlive node death): the fallback would
                        # fork LOCAL processes for a nonexistent node,
                        # forever.
                        if (node_id != self.local_node_id
                                and node_id not in self.node_daemons):
                            continue
                        fresh = sum(
                            1 for w in self.workers.values()
                            if w.node_id == node_id and w.state == IDLE
                            and not w.used and w.conn.alive
                        )
                        pending = self._spawn_pending.get(node_id, 0)
                        live = sum(
                            1 for w in self.workers.values()
                            if w.node_id == node_id
                            and w.state in (STARTING, IDLE, LEASED)
                        )
                        hard = max(cap, 1) * \
                            self.config.worker_pool_hard_cap_multiple
                        room = hard - (live + pending)
                        for _ in range(
                                min(spares - fresh - pending, room)):
                            self._spawn_worker(node_id)
                # Spawn-timeout: reclaim slots of workers that never
                # registered so _maybe_spawn can retry.
                for node_id, times in self._spawn_times.items():
                    while times and now - times[0] > cfg.worker_register_timeout_s:
                        times.popleft()
                        if self._spawn_pending.get(node_id, 0) > 0:
                            self._spawn_pending[node_id] -= 1
                # Stale parked tasks: a node that can neither free nor spawn
                # a worker within the register window gives the task back to
                # the global queue (sticky placement must not become a
                # deadlock when a node's pool is wedged).
                stale_after = cfg.worker_register_timeout_s * 2
                requeued = False
                for node_id in list(self.node_parked):
                    q = self.node_parked[node_id]
                    for task in [
                        t for t in q
                        if t.state == PENDING
                        and now - t.park_time > stale_after
                    ]:
                        self._unpark(task)
                        self._enqueue_task(task)
                        requeued = True
                if requeued:
                    self._kick()
                # Lease TTLs: revoke unrenewed leases; force-reclaim ones
                # whose revoke handshake never completed (dead/wedged
                # client) so slots always flow back to the pool.
                for lease_id in list(self.leases):
                    lease = self.leases.get(lease_id)
                    if lease is None:
                        continue
                    deadline = lease["revoke_deadline"]
                    if deadline is not None:
                        if now >= deadline:
                            self._finalize_lease(
                                lease_id, "revoke_timeout", revoked=True)
                    elif now >= lease["expires"]:
                        await self._revoke_lease(lease_id, "ttl_expired")
                await self._check_memory_pressure()
            except asyncio.CancelledError:
                return
            except Exception:
                import traceback

                traceback.print_exc()

    # -- memory monitor (reference: src/ray/common/memory_monitor.h:52 +
    # raylet/worker_killing_policy_group_by_owner.h) -------------------------

    def _pick_oom_victim(self, node_id: NodeID) -> Optional[WorkerState]:
        """Retriable leased tasks first, newest first; actors and
        non-retriable work only as a last resort never — killing state-
        bearing actors trades a recoverable stall for data loss
        (reference: worker_killing_policy_group_by_owner.h prefers
        retriable tasks, LIFO)."""
        candidates = []
        for w in self.workers.values():
            if w.node_id != node_id or w.state != LEASED or not w.inflight:
                continue
            task = self.tasks.get(next(iter(w.inflight)))
            if task is None:
                continue
            retriable = task.retries_left != 0
            candidates.append((retriable, task.start_time, w))
        if not candidates:
            return None
        candidates.sort(key=lambda c: (not c[0], -c[1]))
        return candidates[0][2]

    async def _check_memory_pressure(self):
        thr = self.config.memory_usage_threshold
        if not thr:
            return
        for node_id in list(self.scheduler.nodes):
            if node_id == self.local_node_id:
                from .config import host_memory_used_frac

                frac = host_memory_used_frac()
            else:
                st = self.node_stats.get(node_id) or {}
                frac = st.get("mem_used_frac") or 0.0
            if frac < thr:
                continue
            now = time.monotonic()
            if now - self._last_oom_kill.get(node_id, 0.0) < 5.0:
                continue  # let the last kill take effect / stats refresh
            victim = self._pick_oom_victim(node_id)
            if victim is None:
                continue
            self._last_oom_kill[node_id] = now
            self._event("oom_kill", worker=victim.worker_id.hex(),
                        mem_used_frac=round(frac, 4))
            self._oom_kills[victim.worker_id] = now
            while len(self._oom_kills) > 1000:  # bound: evict oldest
                self._oom_kills.popitem(last=False)
            if victim.node_id == self.local_node_id:
                try:
                    os.kill(victim.pid, 9)
                except (ProcessLookupError, PermissionError):
                    pass
            else:
                daemon = self.node_daemons.get(victim.node_id)
                if daemon is not None:
                    try:
                        await daemon.push("kill_worker", {"pid": victim.pid})
                    except Exception:
                        pass

    async def stop(self):
        try:
            self.persist_state()
        except Exception:
            pass
        self._shutdown = True
        # Sweep this session's node-local fn-table cache (workers populate
        # it under /tmp/ray_tpu_fncache/<session>).  Off-loop: a large
        # cache tree would stall the final pushes/acks below (RT001) —
        # and after the shutdown flag, so nothing new interleaves in.
        try:
            import shutil

            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: shutil.rmtree(
                    os.path.join("/tmp/ray_tpu_fncache", self.session),
                    ignore_errors=True,
                ),
            )
        except Exception:
            pass
        if self._periodic_task is not None:
            self._periodic_task.cancel()
        if self._tick_task is not None:
            self._tick_task.cancel()
        for w in self.workers.values():
            if w.conn.alive:
                try:
                    await w.conn.push("shutdown", {})
                except Exception:
                    pass
        await asyncio.sleep(0.05)
        with self._pids_lock:
            procs = list(self.worker_procs)
            pids = list(self.worker_pids)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        # Off-loop: an in-flight spawn can hold the mutex across its whole
        # handshake (seconds) and the loop must keep serving until then.
        def _close_zygote():
            with self._zygote_mutex:
                if self._zygote is not None:
                    self._zygote.close()

        await asyncio.get_running_loop().run_in_executor(None, _close_zygote)
        if getattr(self, "_bulk_server", None) is not None:
            self._bulk_server.close()
        await self.server.stop()
        self.store.shutdown()

    def add_local_node(self, resources: Dict[str, float], num_workers: int,
                       labels: Optional[Dict[str, str]] = None,
                       node_id: Optional[NodeID] = None) -> NodeID:
        # ``node_id``: a standalone head (head_main) pins its local node id
        # across restarts so pre-crash object locations, driver node
        # bindings, and resync manifests keep resolving to "this node".
        if node_id is None:
            node_id = NodeID.from_random()
        self.scheduler.add_node(node_id, resources, labels)
        self.local_node_id = node_id
        self.node_sessions[node_id] = self.session
        self.node_worker_caps[node_id] = num_workers
        self.node_worker_counts[node_id] = 0
        self._spawn_pending[node_id] = 0
        self.node_object_addrs[node_id] = f"{self.host}:{self.port}"
        try:
            from .node_main import BulkServer

            self._bulk_server = BulkServer(self.store, self.session, self.host)
            self._bulk_server.start()
            self.node_bulk_addrs[node_id] = f"{self.host}:{self._bulk_server.port}"
        except Exception:
            self._bulk_server = None
        # Boot the local zygote eagerly: its one-time import cost overlaps
        # driver startup instead of delaying the first worker spawn.
        # Try-acquire, never block: do_spawn holds the mutex across whole
        # spawn handshakes on executor threads, and this runs on the loop —
        # if it's contended, a spawn is already booting the zygote for us.
        if self._zygote_mutex.acquire(blocking=False):
            try:
                if self._zygote is None:
                    try:
                        from .zygote import Zygote

                        self._zygote = Zygote(  # rt-unguarded: mutex IS held (try-acquired above; a with-block would stall the loop)
                            self._worker_base_env(node_id))
                    except Exception:
                        self._zygote = None  # rt-unguarded: mutex IS held (try-acquired above)
            finally:
                self._zygote_mutex.release()
        return node_id

    def _worker_base_env(self, node_id: NodeID) -> Dict[str, str]:
        env = dict(os.environ)
        # CPU workers must not claim the TPU: strip accelerator-session env so
        # plugin sitecustomize hooks (axon tunnel, libtpu) stay dormant.  The
        # analog of the reference's TPU_VISIBLE_CHIPS isolation
        # (python/ray/_private/accelerators/tpu.py:155) — a worker only sees
        # chips explicitly granted to it.
        for k in list(env):
            if k.startswith(("PALLAS_AXON", "TPU_", "AXON_")):
                env.pop(k)
        # Ensure workers can import ray_tpu regardless of the driver's cwd.
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_parent
        )
        env.update(
            RT_HEAD_ADDR=f"{self.host}:{self.port}",
            RT_NODE_ID=node_id.hex(),
            RT_SESSION=self.node_sessions[node_id],
            # Peer-plane wiring: the host the worker's peer RPC server
            # binds.  (The node's object-plane endpoints travel via the
            # register reply / resolve_actor descriptors, not env.)
            RT_PEER_HOST=self.host,
            # Workers default to CPU so they never grab the TPU from under the
            # driver; tasks that need the chip opt in via resources={"TPU": n}
            # + runtime_env (see worker_main._maybe_enable_tpu).
            JAX_PLATFORMS=env_jax_platform(),
        )
        return env

    def _spawn_worker(self, node_id: NodeID):
        """Spawn a worker process for a node (local nodes only; remote nodes
        get a spawn_worker push to their daemon)."""
        env = self._worker_base_env(node_id)
        daemon = self.node_daemons.get(node_id)
        self._spawn_pending[node_id] = self._spawn_pending.get(node_id, 0) + 1
        self._spawn_times.setdefault(node_id, deque()).append(time.monotonic())
        if daemon is not None:
            asyncio.ensure_future(daemon.push("spawn_worker", {}))
            return
        from .node_main import LOG_ROOT

        log_dir = os.path.join(LOG_ROOT, self.session)
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{time.time_ns()}.log")

        # Spawn off-loop: the zygote handshake (or a fallback interpreter
        # boot) must never block the control plane's event loop.
        def do_spawn():
            from .zygote import spawn_with_fallback

            with self._zygote_mutex:
                self._zygote, pid, proc = spawn_with_fallback(
                    self._zygote, env, log_path
                )
                with self._pids_lock:
                    if pid is not None:
                        self.worker_pids.append(pid)
                    else:
                        self.worker_procs.append(proc)

        asyncio.get_running_loop().run_in_executor(None, do_spawn)

    # ------------------------------------------------------------- handlers

    async def h_ping(self, conn, body):
        return {"ok": True, "session": self.session}

    async def h_register(self, conn, body):
        from . import schema as wire_schema
        from .rpc import RpcError

        try:
            wire_schema.check_protocol(body.get("protocol"))
        except wire_schema.SchemaError as e:
            raise RpcError(str(e)) from None
        kind = body["kind"]
        reconnect = bool(body.get("reconnect"))
        if kind == "worker":
            worker_id = WorkerID(body["worker_id"])
            node_id = NodeID(body["node_id"])
            if reconnect:
                # Field-state resync: a worker that survived a head restart
                # (or a connection blip) re-registers carrying its live
                # state.  Adoption may be REFUSED (stale actor incarnation,
                # dead actor) — then nothing is registered and the worker
                # exits.
                refused = await self._resync_worker_check(worker_id, body)
                if refused is not None:
                    self._event("worker_resync_refused",
                                worker=worker_id.hex(), reason=refused)
                    return {"session": self.session, "refused": refused}
            w = WorkerState(worker_id, node_id, conn, body.get("pid", 0))
            w.peer_addr = body.get("peer_addr") or ""
            old = self.workers.get(worker_id)
            if old is not None and old.conn is not conn:
                # The previous connection's disconnect may not have fired
                # yet: unlink it so its eventual teardown can't kill the
                # adopted record.
                self.conn_to_worker.pop(old.conn.conn_id, None)
            self.workers[worker_id] = w
            self.conn_to_worker[conn.conn_id] = worker_id
            conn.meta["kind"] = "worker"
            conn.meta["reader_node"] = node_id
            self._log_register(worker_id.hex(), "worker", node_id,
                               body.get("pid", 0), body.get("log_path", ""))
            if not reconnect and self._spawn_pending.get(node_id, 0) > 0:
                self._spawn_pending[node_id] -= 1
                times = self._spawn_times.get(node_id)
                if times:
                    times.popleft()
            self.node_worker_counts[node_id] = (
                self.node_worker_counts.get(node_id, 0) + 1
            )
            if reconnect:
                # Push handlers are already installed in the reconnecting
                # process — no worker_ready handshake: go straight to
                # service (IDLE, or ACTOR when an adoption bound an actor).
                w.used = True
                w.state = IDLE
                await self._resync_worker_adopt(w, body)
                self._note_resync("worker", worker_id.hex())
                self._kick()
            return {"session": self.session,
                    "trace_sample_rate": self.config.trace_sample_rate}
        if kind == "node":
            node_id = NodeID(body["node_id"]) if body.get("node_id") else NodeID.from_random()
            if node_id not in self.scheduler.nodes:
                self.scheduler.add_node(
                    node_id, body["resources"], body.get("labels"))
            self.node_sessions[node_id] = body.get("store_session", self.session)
            self.node_worker_caps[node_id] = body.get("num_workers", 4)
            if reconnect:
                # Blip case: workers of this node may have re-registered
                # BEFORE their daemon did — never zero a count they already
                # rebuilt.
                self.node_worker_counts.setdefault(node_id, 0)
                self._spawn_pending.setdefault(node_id, 0)
            else:
                self.node_worker_counts[node_id] = 0
                self._spawn_pending[node_id] = 0
            self.node_daemons[node_id] = conn
            if body.get("object_addr"):
                self.node_object_addrs[node_id] = body["object_addr"]
            if body.get("bulk_addr"):
                self.node_bulk_addrs[node_id] = body["bulk_addr"]
            self.node_last_ack[node_id] = time.monotonic()
            conn.meta["kind"] = "node"
            conn.meta["node_id"] = node_id
            self._log_register(node_id.hex(), "node", node_id,
                               body.get("pid", 0), body.get("log_path", ""))
            if reconnect:
                resync = body.get("resync") or {}
                self._note_resync("node", node_id.hex(),
                                  headless_s=resync.get("headless_s"))
            self._kick()
            return {"session": self.session, "node_id": node_id.binary(),
                    "trace_sample_rate": self.config.trace_sample_rate}
        # Drivers on the head host attach its shm session for zero-copy
        # reads.  A driver on another machine gets PROXY mode instead (the
        # Ray Client role — reference: python/ray/util/client/, ray_client
        # .proto): no shm attach, no location preference; puts upload in
        # chunks to the head's store (h_proxy_put) and gets pull over the
        # object-plane TCP endpoints like any cross-node read.
        peer = conn.writer.get_extra_info("peername")
        peer_ip = peer[0] if peer else ""
        if peer_ip.startswith("::ffff:"):  # IPv4-mapped (dual-stack socket)
            peer_ip = peer_ip[len("::ffff:"):]
        remote = peer_ip and peer_ip not in ("127.0.0.1", "::1", self.host)
        if remote or body.get("force_proxy"):
            conn.meta["kind"] = kind  # driver (proxied)
            conn.meta["pid"] = body.get("pid")
            conn.meta["proxy"] = True
            return {"session": self.session, "proxy": True,
                    "trace_sample_rate": self.config.trace_sample_rate}
        conn.meta["kind"] = kind  # driver
        conn.meta["pid"] = body.get("pid")
        conn.meta["reader_node"] = self.local_node_id
        if body.get("reconnect"):
            # Same-process driver re-dial (client._try_reconnect): its
            # cumulative counters were folded into the retired baseline at
            # disconnect and are about to be re-reported live.  Mark the
            # connection so the first metrics report un-retires them — an
            # explicit marker, never pid heuristics (a recycled pid from an
            # unrelated process must not decrement the baseline).
            conn.meta["reconnected_pid"] = body.get("pid")
        return {
            "session": self.session,
            "node_id": self.local_node_id.binary() if self.local_node_id else b"",
            # Head-configured root sampling rate: one cluster-wide knob
            # (util/tracing.py rolls it at every trace root).
            "trace_sample_rate": self.config.trace_sample_rate,
        }

    # -- field-state resync (head restart survival) ---------------------------
    # (reference: GCS FT — on a GCS restart, raylets and core workers
    # reconnect and replay their local state so the volatile tables are
    # rebuilt from the field; redis_store_client.h holds only the durable
    # tables.  Here: workers re-register carrying their live actor +
    # creation spec, node daemons replay their store manifests through
    # put_object_batch, and drivers re-assert their large puts.)

    def _note_resync(self, kind: str, proc_hex: str,
                     headless_s: Optional[float] = None):
        self.builtin_metrics.resync_reports.inc(tags={"kind": kind})
        if headless_s is not None and kind == "node":
            self.builtin_metrics.headless_seconds.set(
                float(headless_s), tags={"node": proc_hex})
        self._event("head_resync", peer_kind=kind, proc=proc_hex)

    async def _resync_worker_check(self, worker_id: WorkerID,
                                   body) -> Optional[str]:
        """Decide whether a reconnecting worker's claimed state can be
        adopted.  Returns a refusal reason, or None to adopt.  The refusal
        cases are exactly the stale-incarnation ones: the cluster has (or
        is creating) a NEWER incarnation of the claimed actor, so the old
        process's state must not re-enter the directory."""
        resync = body.get("resync") or {}
        raw_actor = resync.get("actor_id")
        if not raw_actor:
            return None  # plain pooled worker: always adoptable
        actor = self.actors.get(ActorID(raw_actor))
        if actor is None:
            # Unknown actor (head restarted): adoptable iff the worker
            # shipped a usable creation spec to rebuild the record from.
            creation = resync.get("creation_spec")
            if not isinstance(creation, dict) or not creation.get("task_id"):
                return "unknown_actor_without_creation_spec"
            meta = creation.get("actor_meta") or {}
            name = meta.get("name")
            if name and self.named_actors.get(name) not in (None, ActorID(raw_actor)):
                return "actor_name_taken_by_newer_incarnation"
            return None
        if actor.state == "DEAD":
            return "actor_dead"
        if actor.state in ("PENDING", "RESTARTING"):
            # A replacement incarnation is already being created (this
            # head watched the old connection die and started the restart):
            # the returning process is the STALE incarnation.
            return "stale_incarnation"
        if actor.worker_id is not None and actor.worker_id != worker_id:
            w = self.workers.get(actor.worker_id)
            if w is not None and w.conn.alive:
                return "stale_incarnation"
        return None

    async def _resync_worker_adopt(self, w: WorkerState, body) -> None:
        """Bind a reconnecting worker's claimed live actor (check already
        passed).  Unknown actors are rebuilt full-fidelity from the shipped
        creation spec — field state merges with (and preempts) the durable
        snapshot's deferred named-actor replay."""
        resync = body.get("resync") or {}
        raw_actor = resync.get("actor_id")
        if not raw_actor:
            return
        aid = ActorID(raw_actor)
        actor = self.actors.get(aid)
        if actor is None:
            creation = dict(resync.get("creation_spec") or {})
            meta = creation.pop("actor_meta", None) or {}
            spec = {
                "actor_id": raw_actor,
                "class_name": meta.get("class_name")
                or str(creation.get("name", "")).split(".", 1)[0],
                "name": meta.get("name"),
                "namespace": meta.get("namespace"),
                "max_restarts": meta.get("max_restarts", 0),
                "max_task_retries": meta.get("max_task_retries", 0),
                "method_names": meta.get("method_names", []),
                "method_defaults": meta.get("method_defaults", {}),
                "lifetime": meta.get("lifetime"),
                "creation_task": creation,
            }
            actor = ActorRecord(aid, spec)
            self.actors[aid] = actor
            name = spec.get("name")
            if name:
                # The live instance wins over the snapshot's replay: drop
                # the deferred re-creation and any tombstone for the name.
                self.named_actors[name] = aid
                self._restore_named_pending.pop(name, None)
                self.named_tombstones.pop(name, None)
                self._mark_dirty()
            # A later worker death restarts the adopted actor through the
            # normal path: the shipped creation spec is complete (func_key,
            # args), so _handle_worker_death can resubmit it.
            self._event("actor_adopted", actor=aid.hex(),
                        worker=w.worker_id.hex())
        actor.state = "ALIVE"
        actor.worker_id = w.worker_id
        actor.node_id = w.node_id
        w.state = ACTOR
        w.actor_id = aid
        await self._publish(f"actor:{aid.hex()}", {"state": "ALIVE"})
        # Refresh client route caches with the (unchanged) peer address —
        # clients that dropped the route during the outage re-learn it
        # without a resolve round trip.
        await self._publish_actor_event(actor, "ALIVE")
        # Submissions that raced ahead of this adoption were parked: they
        # re-enter now, in arrival order, ahead of anything newer.
        await self._drain_parked_unknown_actor_tasks()
        if actor.pending_tasks:
            await self._drain_actor_queue(actor)

    async def _on_disconnect(self, conn: Connection):
        # Non-detached placement groups die with their creator's connection
        # (reference: PGs are destroyed when the creating job exits unless
        # lifetime="detached" — gcs_placement_group_manager job scoping).
        owned = [p for p, owner in self.pg_owner_conn.items()
                 if owner == conn.conn_id]
        for pg_id in owned:
            self.pg_owner_conn.pop(pg_id, None)
            self.pg_bodies.pop(pg_id, None)
            self.pending_pgs.pop(pg_id, None)
            self._notify_pg_ready(pg_id)
            self.scheduler.remove_placement_group(pg_id)
            self._mark_dirty()
        if owned:
            self._kick()  # freed reservations: retry pending PGs/tasks
        # A proxy driver that died mid-upload leaves unsealed segments in
        # the head store; reclaim them (gets on those ids keep blocking
        # until their own timeouts, same as a never-sealed put).
        for oid in conn.meta.pop("proxy_uploads", ()):  # type: ignore[misc]
            self._proxy_uploads.pop(oid, None)
            try:
                self.store.free(oid, pool=False)
            except Exception:
                pass
        # Leases owned by a departing client release immediately (their
        # resources and workers return to the pool — the driver-disconnect
        # analog of lease return).
        for lease_id, lease in list(self.leases.items()):
            if lease["conn_id"] == conn.conn_id:
                self._finalize_lease(lease_id, "owner_disconnected")
        worker_id = self.conn_to_worker.pop(conn.conn_id, None)
        if conn.meta.get("pid") is not None:
            self._retire_metrics(conn.meta["pid"])
        if worker_id is not None:
            w = self.workers.get(worker_id)
            if w is not None:
                self._retire_metrics(w.pid)
            if w is not None:
                # Exited zygote-forked worker: drop the pid now so a later
                # shutdown can't signal a recycled pid.
                with self._pids_lock:
                    if w.pid in self.worker_pids:
                        self.worker_pids.remove(w.pid)
            await self._handle_worker_death(worker_id)
        node_id = conn.meta.get("node_id")
        if node_id is not None and conn.meta.get("kind") == "node":
            self._log_mark_dead(node_id.hex())
            self.node_daemons.pop(node_id, None)
            self.node_object_addrs.pop(node_id, None)
            self.node_bulk_addrs.pop(node_id, None)
            self.node_last_ack.pop(node_id, None)
            # Fold the dead node's cumulative store counters into the
            # retained baseline first — the cluster-wide *_total store
            # gauges must not drop when a node leaves (same monotonicity
            # rule as _retire_metrics).
            st = self.node_stats.pop(node_id, None)
            for k, v in (((st or {}).get("store")) or {}).items():
                if k.endswith("_total") or k.startswith("gets_"):
                    if isinstance(v, (int, float)):
                        self._store_retired[k] = \
                            self._store_retired.get(k, 0) + v
            damaged = self.scheduler.remove_node(node_id)
            if damaged:
                # Bundles lost with the node get re-placed on survivors
                # (reference: gcs_placement_group_scheduler.h reschedules on
                # node death); until then tasks targeting them stay queued.
                self.pgs_needing_bundles.update(damaged)
            # Tasks committed to the dead node go back to the global queue
            # (their resources died with the node — release is a no-op).
            for task in self.node_parked.pop(node_id, ()):
                if task.state == PENDING:
                    task.parked_node = None
                    self._enqueue_task(task)
            # Objects whose only copy lived there are gone; purge locations
            # and recompute referenced ones from lineage (reference:
            # object_recovery_manager.h:90 recovers on location loss).
            lost: List[ObjectID] = []
            for o, rec in self.objects.items():
                if node_id in rec.locations:
                    rec.locations.discard(node_id)
                    if rec.sealed and rec.inline is None and not rec.locations:
                        lost.append(o)
            for o in lost:
                self._maybe_reconstruct(o)
            # The dead node may have had zero registered workers (the sticky-
            # placement case: parked task, worker still spawning) — the
            # per-worker death path below won't run, so kick explicitly for
            # the requeued tasks and lost-bundle rescheduling.
            self._kick()
            for w in [w for w in self.workers.values() if w.node_id == node_id]:
                # The daemon is gone but its worker processes may still be
                # alive (e.g. simulated node removal): tell them to exit.
                if w.conn.alive:
                    try:
                        await w.conn.push("exit", {})
                    except Exception:
                        pass
                await self._handle_worker_death(w.worker_id)
        for topic_subs in self.subs.values():
            topic_subs.discard(conn.conn_id)

    # -- KV (reference: gcs_kv_manager.h) -------------------------------------

    def _mark_dirty(self):
        self._state_dirty = True

    async def h_kv_put(self, conn, body):
        key = body["key"]
        if body.get("overwrite", True) or key not in self.kv:
            self.kv[key] = body["value"]
            self._mark_dirty()
            return {"added": True}
        return {"added": False}

    async def h_kv_get(self, conn, body):
        return {"value": self.kv.get(body["key"])}

    async def h_kv_del(self, conn, body):
        deleted = self.kv.pop(body["key"], None) is not None
        if deleted:
            self._mark_dirty()
        return {"deleted": deleted}

    async def h_kv_keys(self, conn, body):
        prefix = body.get("prefix", "")
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    # -- objects ---------------------------------------------------------------

    async def h_put_object(self, conn, body):
        """Driver/worker ray.put: object already written to shm (or inline)."""
        oid = ObjectID(body["object_id"])
        if body.get("from_pull") and oid not in self.objects:
            # The object's last reference was dropped mid-pull: registering
            # the new copy would resurrect a freed record with no remaining
            # owner.  Drop the copy instead: adopt it into its node's store
            # (so the daemon owns the segment) and free it immediately.
            node_id = NodeID(body["node_id"])
            if node_id == self.local_node_id:
                try:
                    self.store.adopt(oid)
                except (FileNotFoundError, MemoryError):
                    pass
                self.store.free(oid)
            else:
                daemon = self.node_daemons.get(node_id)
                if daemon is not None:
                    await daemon.push("adopt_object", {"object_id": oid.binary()})
                    await daemon.push("free_objects", {"object_ids": [oid.binary()]})
            return {"freed": True}
        rec = self._obj(oid)
        if body.get("error") is not None:
            # Deferred registration of a direct-call failure result: the
            # submitter shares the ref with another process, which must see
            # the same exception a local get() raises.
            rec.error = body["error"]
        elif body.get("inline") is not None:
            rec.inline = body["inline"]
            rec.size = len(rec.inline)
        else:
            rec.size = body["size"]
            node_id = NodeID(body["node_id"])
            rec.locations.add(node_id)
            self._adopt_local(oid, node_id)
        rec.sealed = True
        rec.ref_count = max(rec.ref_count, 1)
        self._notify_object_ready(oid)
        return {}

    async def h_proxy_put(self, conn, body):
        """Chunked upload from a proxied (off-host) driver into the head's
        store — the Ray Client put path (reference: util/client/dataclient.py
        streams puts to the proxy server in chunks)."""
        oid = ObjectID(body["object_id"])
        total = body["total"]
        view = self._proxy_uploads.get(oid)
        if view is None:
            view = self._proxy_uploads[oid] = self.store.create(oid, total)
            # Track per connection: a proxy driver dying mid-upload must
            # not leak the unsealed segment (cleaned in _on_disconnect).
            conn.meta.setdefault("proxy_uploads", set()).add(oid)
        data = body["data"]
        off = body["offset"]
        if len(data) >= (1 << 20):
            from ray_tpu import _native

            _native.copy(view[off:off + len(data)], data)
        else:
            view[off:off + len(data)] = data
        if body.get("done"):
            self._proxy_uploads.pop(oid, None)
            conn.meta.get("proxy_uploads", set()).discard(oid)
            self.store.seal(oid)
            rec = self._obj(oid)
            rec.size = total
            rec.locations.add(self.local_node_id)
            rec.sealed = True
            rec.ref_count = max(rec.ref_count, 1)
            self._notify_object_ready(oid)
        return {}

    # -- persistence (reference: redis_store_client.h — GCS tables survive a
    # head restart; raylets/workers reconnect and replay) -------------------

    def persist_state(self):
        """Snapshot durable control-plane state: the KV table and the specs
        of live named actors (recreated — fresh — on restore; their in-memory
        state is the application's to checkpoint).  Only when dirty, and the
        pickle+write runs off the event loop (a large KV must not stall
        dispatch)."""
        path = self.config.head_state_path
        if not path or not self._state_dirty:
            return
        self._state_dirty = False
        named = {}
        for name, aid in self.named_actors.items():
            actor = self.actors.get(aid)
            if actor is not None and actor.state != "DEAD":
                named[name] = actor.spec
        # Restored-but-not-yet-replayed named actors (resync grace window
        # still open) must survive a crash-during-restore: carry them
        # through verbatim.
        for name, spec in self._restore_named_pending.items():
            named.setdefault(name, spec)
        # Durable tables: KV, named/detached actor specs, and every live
        # placement group's creation body (reserved or still pending) —
        # the reference persists these in Redis-backed GCS tables
        # (gcs_table_storage.h) and replays on restart.
        # Only detached PGs are durable: a non-detached PG's owner (its
        # driver connection) cannot survive a head restart anyway, and
        # persisting it would leak its reservation forever.
        pgs = {pg_id.binary(): body
               for pg_id, body in self.pg_bodies.items()
               if body.get("lifetime") == "detached"}
        snapshot = {"kv": dict(self.kv), "named_actors": named,
                    "pgs": pgs,
                    # Bounded task-event tail: `status`/timeline keep their
                    # RECENT history across restarts (reference:
                    # gcs_task_manager.h:86 task-event store in GCS).  The
                    # snapshot carries a small tail, never the full 100k
                    # ring — any kv/actor/PG dirty-flush would otherwise
                    # re-pickle a multi-MB event blob every time.
                    "task_events": list(self.task_events)[-2000:],
                    "tombstones": dict(self.named_tombstones)}

        def dump():
            # The dirty bit was cleared BEFORE this off-loop write and the
            # executor future is never awaited — so a failed write (disk
            # full, ENOSPC, permissions) must re-arm it itself, or the
            # snapshot stays silently stale forever while the head keeps
            # reporting itself durable.
            try:
                import cloudpickle

                blob = cloudpickle.dumps(snapshot)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except BaseException:
                import traceback

                self._state_dirty = True  # retry on the next periodic tick
                print(
                    "ray_tpu head: persist_state write to "
                    f"{path!r} FAILED — on-disk snapshot is stale and will "
                    "be retried:\n" + traceback.format_exc(),
                    file=sys.stderr, flush=True,
                )

        try:
            asyncio.get_running_loop().run_in_executor(None, dump)
        except RuntimeError:
            dump()  # no loop (e.g. called from stop() teardown path)

    async def restore_state(self):
        """Load a snapshot: KV merges in; named actors are re-created by
        resubmitting their creation specs (args that lived in the old shm
        session are gone — only inline-args actors restore)."""
        # Open the resync grace window unconditionally at boot: a head
        # restarted WITHOUT a snapshot (crash before the first persist, or
        # no state path configured) still receives field reports and
        # reconnected-driver replays in arbitrary order — unknown-actor
        # submissions and orphan completions must park/seal during the
        # window regardless of snapshot presence.  Harmless on a genuinely
        # fresh cluster: legitimate submissions always follow their
        # create_actor on the same connection.
        self._resync_grace_until = (
            time.monotonic() + self.config.head_resync_grace_s
        )
        path = self.config.head_state_path
        if not path or not os.path.exists(path):
            return
        # Disk read + unpickle off-loop: a multi-MB snapshot parsed on the
        # loop would block the very first registrations after a restart
        # (RT001 — the handlers-never-block contract applies at boot too).
        def _load():
            import cloudpickle

            with open(path, "rb") as f:
                return cloudpickle.loads(f.read())

        state = await asyncio.get_running_loop().run_in_executor(None, _load)
        self.kv.update(state.get("kv", {}))
        # Event history first, so restart markers sort after it.
        for ev in state.get("task_events", []):
            self.task_events.append(ev)
        self._event("head_restarted")
        self.builtin_metrics.head_restarts.inc()
        self.named_tombstones.update(state.get("tombstones", {}))
        # PGs first: restored actors may target them.  Replaying the
        # creation body re-reserves bundles on the current node set; with
        # no nodes registered yet the PG queues in pending_pgs and is
        # satisfied when daemons (re)join — exactly the pending-PG path.
        for raw, body in state.get("pgs", {}).items():
            pg_id = PlacementGroupID(raw)
            if pg_id in self.pg_bodies:
                continue
            try:
                await self.h_create_placement_group(None, body)
            except Exception as e:
                # A skipped PG must be VISIBLE: post-mortems need to know
                # what did not come back, not infer it from a hang.
                self._event("head_restore_skipped", entity="placement_group",
                            id=pg_id.hex(), reason=repr(e))
                print(
                    "ray_tpu head: restore skipped placement group "
                    f"{pg_id.hex()}: {e!r}",
                    file=sys.stderr, flush=True,
                )
        # Named actors do NOT replay immediately: the field may still hold
        # the live instances (workers survive a head restart in headless
        # mode and re-register carrying their actors).  Stage the specs and
        # let the periodic loop replay whatever the resync grace window
        # leaves unclaimed — adoption of a live actor always beats
        # re-creating it fresh.
        staged = 0
        for name, spec in state.get("named_actors", {}).items():
            if name in self.named_actors:
                continue
            self._restore_named_pending[name] = spec
            staged += 1
        if staged:
            self._restore_named_deadline = (
                time.monotonic() + self.config.head_resync_grace_s
            )

    async def _replay_named_actor(self, name: str, spec: dict):
        """Re-create one snapshot-restored named actor that no field report
        claimed within the resync grace window."""
        if name in self.named_actors:
            return  # adopted (or re-created by a client) meanwhile
        ct = spec.get("creation_task", {})
        if ct.get("arg_ids") or ct.get("args_ref"):
            # Constructor args lived in the old session's shm — a
            # resubmit would dep-block forever and wedge the name.
            # Tombstone it so get_actor(name) explains the loss
            # instead of a bare "no actor with name".
            self.named_tombstones[name] = (
                "lost in head restart: the actor's constructor "
                "arguments lived in the previous session's object "
                "store and are not durable; re-create it with "
                "inline-serializable arguments to survive restarts"
            )
            self._event("head_restore_skipped", entity="named_actor",
                        id=name, reason="constructor args not durable")
            self._mark_dirty()
            return
        try:
            await self.h_create_actor(None, spec)
        except Exception as e:
            self._event("head_restore_skipped", entity="named_actor",
                        id=name, reason=repr(e))
            print(
                f"ray_tpu head: restore skipped named actor {name!r}: "
                f"{e!r}",
                file=sys.stderr, flush=True,
            )

    async def h_batch(self, conn, body):
        """Mixed fire-and-forget batch: one RPC carries many submissions /
        task_done reports (clients batch bursts; per-message head processing
        is the control-plane throughput bound)."""
        for entry in body["entries"]:
            fn = self.server.handlers.get(entry["method"])
            if fn is None:
                continue
            try:
                result = fn(conn, entry["body"])
                if asyncio.iscoroutine(result):
                    await result
            except Exception:
                # Per-entry isolation: one bad spec must not drop the rest
                # of the batch (their callers would block forever).
                import traceback

                traceback.print_exc()
        return {}

    async def h_metrics_report(self, conn, body):
        """Per-process metric snapshots; the head keeps the latest rows per
        reporting pid and aggregates on read (reference: stats exported to
        the node metrics agent, src/ray/stats/metric_exporter.h)."""
        pid = body["pid"]
        stale = None
        if conn.meta.get("reconnected_pid") == pid:
            # Register-declared: only a driver that re-dialed with
            # reconnect=True (same process, same cumulative counters) may
            # un-retire its rows — a bare-pid match would let an unrelated
            # process with a recycled/colliding pid permanently decrement
            # the retired baseline.  The marker is consumed only once a
            # retired snapshot actually exists: on a half-open connection
            # the NEW conn's first report can land before the OLD conn's
            # disconnect is processed (which is when _retire_metrics folds
            # the rows in) — popping the marker early would leave that
            # later-retired copy permanently double-counted.
            stale = self._retired_by_pid.pop(pid, None)
            if stale is not None:
                conn.meta.pop("reconnected_pid", None)
        if stale:
            # The driver came back: its cumulative rows were folded into
            # the retired baseline at disconnect and are about to be
            # re-reported live — subtract the retired copy or every series
            # it owns doubles.
            for r in stale:
                neg = dict(r)
                neg["value"] = -r.get("value", 0)
                if "sum" in r:
                    neg["sum"] = -r["sum"]
                    neg["count"] = -r.get("count", 0)
                if r.get("buckets"):
                    neg["buckets"] = [-b for b in r["buckets"]]
                self._merge_metric_row(self._metrics_retired, neg)
        self.metrics_by_pid[pid] = body["rows"]
        return {}

    def _sample_telemetry(self):
        """One telemetry tick: refresh the head's built-in gauges and append
        a sample per live series to the retained history ring (the feed
        behind list_state(kind="metrics_history") and the dashboard's
        sparkline panels).  Skipped entirely inside the history's
        min-interval: the cross-process aggregation isn't free and the
        ring would drop the sample anyway."""
        now = time.time()
        if now - getattr(self, "_last_telemetry_sample", 0.0) \
                < self.metrics_history.min_interval_s:
            return
        self._last_telemetry_sample = now
        parked = sum(len(q) for q in self.node_parked.values())
        self.builtin_metrics.queue_depth.set(
            float(len(self.queued_tasks) + parked))
        try:
            # Cluster-wide store totals: the head's own store plus every
            # remote daemon's latest stats push (h_node_stats) — remote
            # nodes have no head-side ObjectStore object, only these dicts.
            totals = dict(self.store.stats())
            for k, v in self._store_retired.items():
                totals[k] = totals.get(k, 0) + v
            for st in self.node_stats.values():
                remote = (st or {}).get("store") or {}
                for k, v in remote.items():
                    if isinstance(v, (int, float)):
                        totals[k] = totals.get(k, 0) + v
            self.builtin_metrics.sample_store(totals)
        except Exception:
            pass
        rows = self.metrics_rows()
        self.metrics_history.record(rows)
        if self.config.health_enabled:
            try:
                self._health_tick(now, rows)
            except Exception:
                logger.exception("health tick failed")

    # -- health / incident plane (util/health.py) -----------------------------

    def _timed(self, name: str, fn):
        """Wrap one registered RPC handler with the per-method wall-time
        histogram (head self-observability: when the loop-lag detector
        fires, these rows say which handler ate the loop)."""
        hist = self.builtin_metrics.rpc_handler
        tags = {"method": name}

        async def timed(conn, body):
            t0 = time.perf_counter()
            try:
                return await fn(conn, body)
            finally:
                hist.observe(time.perf_counter() - t0, tags)

        return timed

    def _health_tick(self, now: float, rows: List[dict]) -> None:
        """One detector pass: hand the aggregated metric rows plus the
        in-window step records / devmem reports to the HealthEngine."""
        cfg = self.config
        horizon = now - max(cfg.health_window_s,
                            cfg.health_slo_slow_window_s / 4)
        steps: List[dict] = []
        for ring in self.engine_steps.values():
            steps.extend(r for r in ring
                         if isinstance(r.get("t"), (int, float))
                         and r["t"] >= horizon)
        profiles: List[dict] = []
        for st in self.gang_rounds.values():
            profiles.extend(pr for pr in st["profiles"]
                            if isinstance(pr.get("t"), (int, float))
                            and pr["t"] >= horizon)
        self.health.tick(
            now, rows, steps, self.devmem_by_pid, self._loop_lag_s,
            slo_targets=self._serve_slo_targets(),
            evidence=self._gather_evidence,
            gang_profiles=profiles)

    def _serve_slo_targets(self) -> Dict[str, float]:
        """TTFT/ITL targets for the burn-rate detector: explicit config
        wins; otherwise the strictest target any serve deployment declared
        (controller publishes them under kv 'serve_slo:<deployment>')."""
        cfg = self.config
        targets = {"ttft": cfg.health_slo_ttft_s, "itl": cfg.health_slo_itl_s}
        declared: Dict[str, List[float]] = {"ttft": [], "itl": []}
        for key, raw in self.kv.items():
            if not key.startswith("serve_slo:"):
                continue
            try:
                spec = json.loads(bytes(raw).decode())
            except Exception:
                continue
            for sig in ("ttft", "itl"):
                t = spec.get(sig)
                if isinstance(t, (int, float)) and t > 0:
                    declared[sig].append(float(t))
        for sig, vals in declared.items():
            if targets[sig] <= 0 and vals:
                targets[sig] = min(vals)
        return {k: v for k, v in targets.items() if v > 0}

    # Evidence callback handed to HealthEngine.tick — runs synchronously
    # inside _health_tick on the head loop.
    def _gather_evidence(self, f: dict, now: float) -> dict:  # rt-role: loop
        """Evidence chain captured when an incident opens: trace ids from
        the timeline ring (newest spans in the suspicion window), recent
        failure-shaped task events, the detector's own counter deltas /
        window stats, and — for head-pressure — the slowest RPC handlers."""
        window = max(60.0, self.config.health_window_s * 2)
        trace_ids: List[str] = []
        events: List[dict] = []
        for ev in reversed(self.task_events):
            if ev.get("ts", 0) < now - window:
                break
            kind = ev.get("kind", "")
            if kind == "span":
                tid = ev.get("trace_id")
                if tid and tid not in trace_ids and len(trace_ids) < 8:
                    trace_ids.append(tid)
            elif len(events) < 8 and any(
                    t in kind for t in ("fail", "death", "timeout",
                                        "lost", "oom", "quarantine")):
                events.append({k: v for k, v in ev.items()
                               if isinstance(v, (str, int, float, bool,
                                                 type(None)))})
        ev_chain: dict = {
            "window_s": window,
            "trace_ids": trace_ids,
            "task_events": events,
        }
        data = f.get("data") or {}
        if "deltas" in data:
            ev_chain["counter_deltas"] = data["deltas"]
        if f["kind"] in ("stall_pressure", "step_jitter"):
            ev_chain["step_window"] = {
                k: v for k, v in data.items() if k != "engine"}
        if f["kind"].startswith("gang_"):
            # Gang incidents: the offending rank/phase plus the worst
            # joined rounds from the suspicion window (the detector
            # already ranked them) — what `ray_tpu doctor` replays.
            for k in ("rank", "phase", "gang", "worst_rounds",
                      "skew_frac", "data_frac", "coll_frac"):
                if k in data:
                    ev_chain[k] = data[k]
        if f["kind"] == "head_pressure":
            rows = self.builtin_metrics.rpc_handler._snapshot()
            slow = sorted(
                ((r.get("tags", {}).get("method", "?"),
                  r.get("sum", 0.0), r.get("count", 0)) for r in rows),
                key=lambda x: -x[1])[:5]
            ev_chain["slowest_handlers"] = [
                {"method": m, "total_s": round(s, 3), "calls": c}
                for m, s, c in slow if c]
        return ev_chain

    # Both incident sinks are HealthEngine callbacks invoked only from
    # _health_tick, i.e. on the head loop inside _periodic_loop.
    def _on_incident_open(self, inc: dict) -> None:  # rt-role: loop
        self.builtin_metrics.incidents_opened.inc(
            1.0, {"kind": inc["kind"]})
        self._event("incident_open", id=inc["id"], incident_kind=inc["kind"],
                    severity=inc["severity"], summary=inc["summary"])
        self._alert("opened", inc)

    def _on_incident_resolve(self, inc: dict) -> None:  # rt-role: loop
        self.builtin_metrics.incidents_resolved.inc(1.0)
        self._event("incident_resolve", id=inc["id"],
                    incident_kind=inc["kind"])
        self._alert("resolved", inc)

    def _alert(self, transition: str, inc: dict) -> None:
        """Push-style alerting: 'log' -> head log WARNING; http(s) URL ->
        fire-and-forget JSON POST on a daemon thread (a dead webhook must
        never block the head loop)."""
        sink = self.config.alert_sink
        if not sink:
            return
        if sink == "log":
            logger.warning("incident %s [%s/%s] %s: %s", transition,
                           inc["kind"], inc["severity"], inc["id"],
                           inc["summary"])
            return
        if sink.startswith("http"):
            payload = json.dumps({
                "transition": transition, "id": inc["id"],
                "kind": inc["kind"], "severity": inc["severity"],
                "summary": inc["summary"], "opened": inc["opened"],
            }).encode()

            def _post():
                import urllib.request
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        sink, data=payload,
                        headers={"Content-Type": "application/json"}),
                        timeout=2.0)
                except Exception:
                    pass  # alerting is best-effort by design

            threading.Thread(target=_post, name="alert-sink",
                             daemon=True).start()

    @staticmethod
    def _merge_metric_row(agg: Dict[tuple, dict], r: dict) -> None:
        key = (r["name"], tuple(sorted(r.get("tags", {}).items())))
        cur = agg.get(key)
        if cur is None:
            agg[key] = dict(r)
        elif r["kind"] == "gauge":
            cur["value"] = r["value"]  # last writer wins
        else:
            cur["value"] = cur.get("value", 0) + r.get("value", 0)
            if "sum" in r:
                cur["sum"] = cur.get("sum", 0) + r["sum"]
                cur["count"] = cur.get("count", 0) + r["count"]
                if r.get("buckets") and cur.get("buckets"):
                    cur["buckets"] = [
                        a + b for a, b in
                        zip(cur["buckets"], r["buckets"])
                    ]

    def _retire_metrics(self, pid: int) -> None:
        """A reporting process disconnected: its counters/histograms must
        stay in the cluster totals (a counter vanishing reads as a negative
        rate to any scraper) — fold them into the retired accumulator.
        Gauges are point-in-time and die with the process."""
        rows = self.metrics_by_pid.pop(pid, None)
        if not rows:
            return
        kept = [r for r in rows if r.get("kind") in ("counter", "histogram")]
        for r in kept:
            self._merge_metric_row(self._metrics_retired, r)
        if kept:
            self._retired_by_pid[pid] = kept
            while len(self._retired_by_pid) > 1000:  # bound: evict oldest
                self._retired_by_pid.pop(next(iter(self._retired_by_pid)))

    def metrics_rows(self) -> List[dict]:
        """Aggregate across processes: counters/histogram counts sum, gauges
        keep the per-process latest.  The head's own built-in instruments
        (pid-less) and the counters of departed processes merge in
        alongside."""
        agg: Dict[tuple, dict] = {}
        for r in self._metrics_retired.values():
            self._merge_metric_row(agg, r)
        sources = dict(self.metrics_by_pid)
        sources[-1] = self.builtin_metrics.rows()  # head-internal builtins
        for pid, rows in sources.items():
            for r in rows:
                self._merge_metric_row(agg, r)
        return list(agg.values())

    async def h_put_object_batch(self, conn, body):
        """Registration batch for inline objects (client-side put buffering:
        one RPC per ~64 small puts instead of one each).  Entries may also
        carry an error blob or a shm descriptor (size + node) — the
        direct-call result registration path rides the same batch so a
        registration can never overtake the submission that references it."""
        for entry in body["objects"]:
            oid = ObjectID(entry["object_id"])
            rec = self._obj(oid)
            if entry.get("error") is not None:
                rec.error = entry["error"]
            elif entry.get("inline") is not None:
                rec.inline = entry["inline"]
                rec.size = len(rec.inline)
            elif entry.get("size") is not None:
                rec.size = entry["size"]
                node_id = NodeID(entry["node_id"])
                rec.locations.add(node_id)
                if not (entry.get("resync") and node_id != self.local_node_id):
                    # Resync manifests come FROM the owning node's daemon —
                    # it already accounts these segments; pushing adopt
                    # back at it for a whole manifest is pure noise.
                    self._adopt_local(oid, node_id)
            rec.sealed = True
            rec.ref_count = max(rec.ref_count, 1)
            self._notify_object_ready(oid)
        return {}

    def _adopt_local(self, oid: ObjectID, node_id: Optional[NodeID]):
        """Account a shm object with its node's store daemon (enables
        eviction, spilling, and shutdown cleanup): local objects go into the
        head's own store; remote ones get an adopt push to the node daemon."""
        if node_id == self.local_node_id:
            try:
                self.store.adopt(oid)
            except (FileNotFoundError, MemoryError):
                pass
            return
        daemon = self.node_daemons.get(node_id)
        if daemon is not None:
            asyncio.ensure_future(
                daemon.push("adopt_object", {"object_id": oid.binary()})
            )

    async def h_restore_object(self, conn, body):
        """Re-materialize a spilled object into shm so a reader can attach."""
        view = self.store.get(ObjectID(body["object_id"]))
        return {"ok": view is not None}

    async def h_store_stats(self, conn, body):
        return self.store.stats()

    async def h_add_object_ref(self, conn, body):
        for raw in body["object_ids"]:
            self._obj(ObjectID(raw)).ref_count += 1
        return {}

    async def h_free_objects(self, conn, body):
        items = []
        for raw in body["object_ids"]:
            oid = ObjectID(raw)
            rec = self.objects.get(oid)
            if rec is None:
                continue
            rec.ref_count -= 1
            if rec.ref_count <= 0:
                self.objects.pop(oid, None)
                self._drop_lineage_for(oid)
                items.append((raw, set(rec.locations)))
        if items:
            await self._deferred_free(items)
        return {"num_freed": len(items)}

    async def _deferred_free(self, items: List[tuple]):
        """Two-phase free: tell the processes that could hold a copy to drop
        it; release (and pool) the segments only after they ack a clean
        detach.  A reader whose user code still holds zero-copy views acks
        *dirty* and the inode is unlinked instead of pooled, so the views
        stay valid on the orphaned inode (reference: plasma keeps a buffer
        alive while any client holds a reference; here the detach-ack is the
        release edge).  Un-acked frees expire conservatively (no pooling)."""
        raws = [raw for raw, _ in items]
        locations: Set[NodeID] = set()
        for _, locs in items:
            locations.update(locs)
        conns = [
            c for c in self.server.connections.values()
            if c.meta.get("kind") in ("driver", "worker")
            and (c.meta.get("reader_node") in locations
                 # Proxy drivers have no node identity but may hold pulled
                 # private copies of anything — always notify them.
                 or c.meta.get("proxy"))
        ]
        if not conns:
            self._finalize_free(items, dirty=set())
            return
        self._free_token += 1
        token = self._free_token
        pf = {
            "items": items,
            "waiting": {c.conn_id for c in conns},
            "dirty": set(),
            "deadline": time.monotonic() + 2.0,
        }
        self._pending_frees[token] = pf
        body = {"object_ids": raws, "ack_token": token}
        for c in conns:
            try:
                await c.push("object_free", body)
            except Exception:
                pf["waiting"].discard(c.conn_id)
        if not pf["waiting"]:
            self._pending_frees.pop(token, None)
            self._finalize_free(items, dirty=set())

    async def h_object_free_ack(self, conn, body):
        pf = self._pending_frees.get(body["token"])
        if pf is None:
            return {}
        pf["waiting"].discard(conn.conn_id)
        pf["dirty"].update(body.get("dirty", ()))
        if not pf["waiting"]:
            self._pending_frees.pop(body["token"], None)
            self._finalize_free(pf["items"], pf["dirty"])
        return {}

    def _expire_pending_frees(self):
        now = time.monotonic()
        for token in list(self._pending_frees):
            pf = self._pending_frees[token]
            if now >= pf["deadline"]:
                self._pending_frees.pop(token, None)
                # Unknown reader state: never pool (views may be live).
                self._finalize_free(
                    pf["items"], dirty={raw for raw, _ in pf["items"]}
                )

    def _finalize_free(self, items: List[tuple], dirty: set):
        no_pool_by_node: Dict[NodeID, List[bytes]] = {}
        by_node: Dict[NodeID, List[bytes]] = {}
        for raw, locs in items:
            oid = ObjectID(raw)
            pool = raw not in dirty
            if not locs or self.local_node_id in locs:
                self.store.free(oid, pool=pool)
            for node_id in locs:
                if node_id == self.local_node_id:
                    continue
                by_node.setdefault(node_id, []).append(raw)
                if not pool:
                    no_pool_by_node.setdefault(node_id, []).append(raw)
        for node_id, raws in by_node.items():
            daemon = self.node_daemons.get(node_id)
            if daemon is not None:
                asyncio.ensure_future(daemon.push("free_objects", {
                    "object_ids": raws,
                    "no_pool": no_pool_by_node.get(node_id, []),
                }))

    def _object_wire(self, rec: ObjectRecord,
                     prefer: Optional[NodeID] = None) -> dict:
        if rec.error is not None:
            return {"error": rec.error}
        if rec.inline is not None:
            return {"inline": rec.inline}
        # Prefer a copy on the reader's own node (shm attach, zero-copy);
        # otherwise a RANDOM live location: each completed pull registers a
        # new replica (from_pull), so a hot object's readers fan out across
        # replicas and a broadcast forms an organic distribution tree
        # instead of hammering the origin node (reference:
        # object_manager.h:125-139 spreads pulls over known locations).
        if prefer is not None and prefer in rec.locations:
            loc = prefer
        elif len(rec.locations) > 1:
            import random as _random

            loc = _random.choice(list(rec.locations))
        else:
            loc = next(iter(rec.locations), None)
        return {
            "size": rec.size,
            "session": self.node_sessions.get(loc, self.session),
            "node_id": loc.binary() if loc else None,
            "addr": self.node_object_addrs.get(loc),
            "bulk_addr": self.node_bulk_addrs.get(loc),
        }

    async def h_get_objects(self, conn, body):
        timeout = body.get("timeout", -1.0)
        deadline = None if timeout < 0 else time.monotonic() + timeout
        prefer = conn.meta.get("reader_node")
        out = []
        for raw in body["object_ids"]:
            oid = ObjectID(raw)
            rec = self._obj(oid)
            while not rec.sealed:
                ev = asyncio.Event()
                self.object_waiters.setdefault(oid, []).append(ev)
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    out.append({"timeout": True})
                    break
                try:
                    await asyncio.wait_for(ev.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    out.append({"timeout": True})
                    break
            else:
                out.append(self._object_wire(rec, prefer))
        return {"objects": out}

    async def h_object_sizes(self, conn, body):
        """Sizes of sealed objects (None while unsealed) — lets the Data
        executor's byte-budget backpressure learn block sizes without
        fetching them (reference: BlockMetadata.size_bytes feeding
        execution/resource_manager.py budgets)."""
        out = []
        for raw in body["object_ids"]:
            rec = self.objects.get(ObjectID(raw))
            out.append(rec.size if rec is not None and rec.sealed else None)
        return {"sizes": out}

    async def h_wait_objects(self, conn, body):
        oids = [ObjectID(raw) for raw in body["object_ids"]]
        num_returns = body.get("num_returns", 1)
        timeout = body.get("timeout", -1.0)
        deadline = None if timeout < 0 else time.monotonic() + timeout

        def ready_ids():
            return [o for o in oids if self.objects.get(o) and self.objects[o].sealed]

        while len(ready_ids()) < num_returns:
            evs = []
            for o in oids:
                rec = self._obj(o)
                if not rec.sealed:
                    ev = asyncio.Event()
                    self.object_waiters.setdefault(o, []).append(ev)
                    evs.append(ev)
            if not evs:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            waits = [asyncio.ensure_future(e.wait()) for e in evs]
            done, pending = await asyncio.wait(
                waits, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            for p in pending:
                p.cancel()
            if not done:
                break
        ready = set(ready_ids())
        return {
            "ready": [o.binary() for o in oids if o in ready],
            "not_ready": [o.binary() for o in oids if o not in ready],
        }

    # -- tasks -----------------------------------------------------------------

    def _register_task(self, task: TaskRecord):
        """Common bookkeeping: return-object lineage, dependency tracking, and
        pinning of argument objects for the task's lifetime (the simplified
        analog of the reference's borrowed-reference pinning,
        reference_count.h:61)."""
        self.tasks[task.task_id] = task
        for raw in task.spec.get("return_ids", []):
            if task.spec.get("_reconstruct"):
                # Freed sibling returns stay freed: resurrecting them via
                # _obj would create unowned records nothing ever decrefs.
                rec = self.objects.get(ObjectID(raw))
                if rec is not None:
                    rec.task_id = task.task_id
            else:
                self._obj(ObjectID(raw)).task_id = task.task_id
        for raw in task.spec.get("arg_ids", []):
            oid = ObjectID(raw)
            rec = self._obj(oid)
            rec.ref_count += 1  # unpinned at task finalization
            if not rec.sealed:
                task.pending_deps.add(oid)
                self.tasks_waiting_on.setdefault(oid, set()).add(task.task_id)

    def _lineage_eligible(self, task: TaskRecord) -> bool:
        retries = task.spec.get(
            "max_retries", self.config.default_task_max_retries
        )
        if not (
            task.state == FINISHED
            and self.config.lineage_max_entries > 0
            and retries != 0  # max_retries=0: reconstruction disabled anyway
            and not task.spec.get("actor_id")
            and not task.spec.get("is_actor_creation")
            and task.spec.get("num_returns") != "streaming"
        ):
            return False
        # Inline returns live in head memory and survive node death — no
        # reconstruction needed, so don't pin args for them.
        return any(
            ObjectID(raw) in self.objects
            and self.objects[ObjectID(raw)].inline is None
            for raw in task.spec.get("return_ids", [])
        )

    def _unpin_spec(self, spec: dict, include_args_ref: bool = True):
        """Release the arg pins held by a lineage entry."""
        for raw in spec.get("arg_ids", []):
            self._decref(ObjectID(raw))
        if include_args_ref and spec.get("args_ref") is not None:
            self._decref(ObjectID(spec["args_ref"]))

    def _drop_lineage_for(self, oid: ObjectID):
        """Drop a task's lineage entry once none of its return objects are
        referenced anymore (the entry exists to recompute exactly those)."""
        tid = oid.task_id()
        spec = self.lineage.get(tid)
        if spec is None:
            return
        if any(ObjectID(raw) in self.objects
               for raw in spec.get("return_ids", [])):
            return
        del self.lineage[tid]
        self.reconstruction_counts.pop(tid, None)
        self._unpin_spec(spec)

    def _fail_object(self, oid: ObjectID, exc: Exception):
        rec = self._obj(oid)
        rec.error = serialization.pack(exc)
        rec.sealed = True
        self._notify_object_ready(oid)

    def _maybe_reconstruct(self, oid: ObjectID, depth: int = 0) -> bool:
        """Recompute a lost object by re-running its creating task (the
        ObjectID embeds it).  Returns True when the object is available, in
        flight, or now being reconstructed; False when it was failed with
        ObjectReconstructionFailedError (reference:
        object_recovery_manager.h:90 RecoverObject)."""
        from ..exceptions import ObjectReconstructionFailedError

        rec = self.objects.get(oid)
        if rec is None:
            return False  # freed: nothing to recover, nobody waiting
        if rec.inline is not None or rec.locations:
            return True
        tid = oid.task_id()
        live = self.tasks.get(tid)
        if live is not None and live.state in (PENDING, RUNNING):
            rec.sealed = False  # already being (re)computed: getters block
            rec.error = None
            return True
        spec = self.lineage.get(tid)
        if spec is None or depth > 8:
            self._fail_object(oid, ObjectReconstructionFailedError(
                f"object {oid.hex()} lost and "
                + ("reconstruction depth limit reached" if spec is not None
                   else "no lineage is available (task spec dropped, "
                        "put object, or max_retries=0)")
            ))
            return False
        retries = spec.get("max_retries", self.config.default_task_max_retries)
        count = self.reconstruction_counts.get(tid, 0)
        if retries >= 0 and count >= max(retries, 0):
            self._fail_object(oid, ObjectReconstructionFailedError(
                f"object {oid.hex()} lost and reconstruction attempts "
                f"exhausted ({count}/{retries})"
            ))
            return False
        self.reconstruction_counts[tid] = count + 1
        # Unseal the still-referenced LOST returns of the task (the re-run
        # recomputes them); freed siblings stay freed — resurrecting them
        # via _obj would create unowned records nothing ever decrefs — and
        # siblings with a live copy (or inline data) must stay readable
        # (a failed re-run must not overwrite them with an error).
        for raw in spec.get("return_ids", []):
            r = self.objects.get(ObjectID(raw))
            if r is not None and r.inline is None and not r.locations:
                r.sealed = False
                r.error = None
        # Recursively recover lost inputs first (their specs are pinned by
        # this entry); the resubmitted task dep-blocks on them via
        # _register_task until they reseal.
        for raw in spec.get("arg_ids", []):
            self._maybe_reconstruct(ObjectID(raw), depth + 1)
        if spec.get("args_ref") is not None:
            self._maybe_reconstruct(ObjectID(spec["args_ref"]), depth + 1)
        run_spec = spec
        strat = spec.get("strategy")
        if strat and strat.get("kind") == "node_affinity":
            nid = NodeID(strat["node_id"])
            node = self.scheduler.nodes.get(nid)
            if node is None or not node.alive:
                # The anchor died with the object; a hard affinity would make
                # the re-run unschedulable forever.
                run_spec = {**spec, "strategy": None}
        if run_spec is spec:
            run_spec = dict(spec)
        run_spec["_reconstruct"] = True
        task = TaskRecord(run_spec)
        self._register_task(task)
        self._event("task_reconstruction", task=tid.hex(),
                    object=oid.hex(), attempt=count + 1)
        if not task.pending_deps:
            self._enqueue_task(task)
        self._kick()
        return True

    async def h_reconstruct_object(self, conn, body):
        """Client-requested recovery (its pull found every location gone)."""
        oid = ObjectID(body["object_id"])
        rec = self.objects.get(oid)
        if rec is not None and rec.sealed and not rec.inline:
            # Drop locations the client proved stale (node died unannounced).
            dead = {
                loc for loc in rec.locations
                if loc != self.local_node_id and loc not in self.node_daemons
            }
            rec.locations -= dead
        return {"queued": self._maybe_reconstruct(oid)}

    def _decref(self, oid: ObjectID):
        rec = self.objects.get(oid)
        if rec is None:
            return
        rec.ref_count -= 1
        if rec.ref_count <= 0:
            self.objects.pop(oid, None)
            self._drop_lineage_for(oid)
            asyncio.ensure_future(
                self._deferred_free([(oid.binary(), set(rec.locations))])
            )

    def _unpin_task_args(self, task: TaskRecord):
        for raw in task.spec.get("arg_ids", []):
            oid = ObjectID(raw)
            self._decref(oid)
            waiting = self.tasks_waiting_on.get(oid)
            if waiting is not None:
                waiting.discard(task.task_id)
                if not waiting:
                    self.tasks_waiting_on.pop(oid, None)

    def _finalize_task(self, task: TaskRecord):
        """Terminal-state cleanup: either transfer the task's arg pins to a
        lineage entry (so a lost output can be recomputed by re-running the
        spec — reference: reference_count.h:75 lineage pinning) or unpin."""
        if self._lineage_eligible(task):
            old = self.lineage.pop(task.task_id, None)
            self.lineage[task.task_id] = task.spec
            if old is not None:
                # Re-recorded after reconstruction: the fresh registration
                # re-pinned arg_ids (but never args_ref — _register_task
                # doesn't pin it), so release only the re-pinned part.
                self._unpin_spec(old, include_args_ref=False)
            while len(self.lineage) > self.config.lineage_max_entries:
                etid, evicted = self.lineage.popitem(last=False)
                self.reconstruction_counts.pop(etid, None)
                self._unpin_spec(evicted)
        else:
            self._unpin_task_args(task)
            # The large-args spill object is pinned only by its creation
            # reference; it dies with the task — except for the creation task
            # of a live actor, whose restart resubmits the same spec and must
            # be able to re-read the args (freed at permanent actor death).
            args_ref = task.spec.get("args_ref")
            # A lineage entry for this task still holds the args_ref pin
            # (e.g. a failed reconstruction re-run): leave it to the entry.
            if args_ref is not None and task.task_id not in self.lineage:
                keep = False
                if task.spec.get("is_actor_creation"):
                    actor = self.actors.get(ActorID(task.spec["actor_id"]))
                    keep = actor is not None and actor.state != "DEAD"
                if not keep:
                    self._decref(ObjectID(args_ref))
        self.finished_tasks.append(
            {
                "task_id": task.task_id.hex(),
                "name": task.spec.get("name", ""),
                "state": task.state,
                "start_time": task.start_time,
                "end_time": task.end_time,
                "error": task.error,
            }
        )
        # Streaming task records stay (next_stream_item consults their state);
        # creation task records stay while the actor lives (its death releases
        # the creation resources via this record).
        if not (
            task.spec.get("num_returns") == "streaming"
            or task.spec.get("is_actor_creation")
        ):
            self.tasks.pop(task.task_id, None)

    async def h_submit_task(self, conn, body):
        task = TaskRecord(body)
        self._register_task(task)
        self._task_transition(task, "SUBMITTED")
        self._event("task_submitted", task=task.task_id.hex(), name=body.get("name", ""))
        if not task.pending_deps:
            self._enqueue_task(task)
            self._kick()
        return {}

    def _enqueue_task(self, task: "TaskRecord", front: bool = False):
        if front:
            self.queued_tasks.appendleft(task)
        else:
            self.queued_tasks.append(task)
        k = task.shape_key()
        self.queue_shapes[k] = self.queue_shapes.get(k, 0) + 1

    def _dequeue_shape(self, task: "TaskRecord"):
        k = task.shape_key()
        n = self.queue_shapes.get(k, 0) - 1
        if n <= 0:
            self.queue_shapes.pop(k, None)
        else:
            self.queue_shapes[k] = n

    async def _dispatch_loop(self):
        """Single dispatch pass: match queued tasks to idle workers.

        The analog of LocalTaskManager::ScheduleAndDispatchTasks
        (reference: src/ray/raylet/local_task_manager.h:58).  Placement is
        *sticky*: once the scheduler picks a node the task acquires that
        node's resources and parks in its per-node queue until a worker
        there is idle — a warm node's workers must not drain the queue while
        a cold node's workers are still starting (reference:
        spread_scheduling_policy.h + local_task_manager.h keep the lease on
        the chosen raylet while its worker pool spins up)."""
        if self._shutdown:
            return
        if self.pgs_needing_bundles:
            self._try_reschedule_bundles()
        if self.pending_pgs:
            self._try_pending_pgs()
        await self._drain_parked()
        made_progress = True
        while made_progress and self.queued_tasks:
            made_progress = False
            requeue: List[TaskRecord] = []
            # Resource shapes that already failed to place this pass: later
            # tasks with the same shape fail identically, so skip them — a
            # 10k-task homogeneous burst costs one placement failure per
            # pass, not 10k (reference: cluster_task_manager.h groups tasks
            # by SchedulingClass for exactly this reason).
            failed_shapes: set = set()
            while self.queued_tasks:
                task = self.queued_tasks.popleft()
                self._dequeue_shape(task)
                if task.state != PENDING:
                    continue
                shape = task.shape_key()
                if shape in failed_shapes:
                    requeue.append(task)
                    if all(k in failed_shapes for k in self.queue_shapes):
                        break  # nothing left in the queue can place
                    continue
                node_id = self.scheduler.pick_node(task.resources, task.strategy)
                if node_id is None:
                    failed_shapes.add(shape)
                    requeue.append(task)
                    if all(k in failed_shapes for k in self.queue_shapes):
                        break  # nothing left in the queue can place
                    continue
                if not self.scheduler.acquire(node_id, task.resources, task.strategy):
                    failed_shapes.add(shape)
                    requeue.append(task)
                    if all(k in failed_shapes for k in self.queue_shapes):
                        break  # nothing left in the queue can place
                    continue
                self._task_transition(task, "SCHEDULED", node=node_id)
                worker = self._find_idle_worker(
                    node_id, fresh=self._needs_chip_grant(task)
                )
                if worker is None:
                    # Commit to the picked node: hold the resources, park
                    # until a worker registers or frees up there.  Actors get
                    # dedicated processes beyond the task-worker cap; plain
                    # tasks respect the cap.
                    self._maybe_spawn(
                        node_id,
                        force=bool(task.spec.get("is_actor_creation"))
                        or self._needs_chip_grant(task),
                    )
                    task.parked_node = node_id
                    task.park_time = time.monotonic()
                    self.node_parked.setdefault(node_id, deque()).append(task)
                    made_progress = True  # resource state changed
                    continue
                if not await self._dispatch(task, worker):
                    # Chip-starved: floats freed up but no concrete chip IDs
                    # yet (a blocked holder's process still maps them).
                    self.scheduler.release(
                        node_id, task.resources, task.strategy
                    )
                    failed_shapes.add(shape)
                    requeue.append(task)
                    continue
                made_progress = True
            # Requeue at the FRONT (reversed) so submission order within a
            # shape survives an early-exit pass.
            for t in reversed(requeue):
                self._enqueue_task(t, front=True)
        if self.queued_tasks and self.leases:
            # Queued work that couldn't place while slots are leased out:
            # preempt the stalest lease so head-scheduled shapes (gangs,
            # TPU grants, bigger bundles) can't be starved by direct-plane
            # reservations.  Age-gated (a momentary queue blip during a
            # burst must not revoke a lease the burst is about to use),
            # one per pass, with a cooldown.
            now = time.monotonic()
            oldest_wait = max(
                (time.time() - t.submit_time for t in self.queued_tasks
                 if t.state == PENDING), default=0.0)
            if oldest_wait > 0.5 and now - self._last_lease_preempt > 0.2:
                candidates = [
                    (lease["expires"], lid)
                    for lid, lease in self.leases.items()
                    if lease["revoke_deadline"] is None
                ]
                if candidates:
                    self._last_lease_preempt = now
                    await self._revoke_lease(min(candidates)[1], "preempted")

    async def _drain_parked(self):
        """Dispatch node-committed tasks to workers that have become idle.
        Resources were acquired at park time — no re-acquire here."""
        for node_id in list(self.node_parked):
            q = self.node_parked.get(node_id)
            while q:
                task = q[0]
                if task.state != PENDING:
                    q.popleft()
                    continue
                worker = self._find_idle_worker(
                    node_id, fresh=self._needs_chip_grant(task)
                )
                if worker is None:
                    self._maybe_spawn(
                        node_id,
                        force=bool(task.spec.get("is_actor_creation"))
                        or self._needs_chip_grant(task),
                    )
                    break
                # Pop BEFORE the dispatch await: a concurrent pass must not
                # see an already-dispatched task at q[0] (it would pop it
                # and this coroutine's pop would then drop the next task).
                q.popleft()
                task.parked_node = None
                if not await self._dispatch(task, worker):
                    # Chip-starved: _dispatch refused before any await, so
                    # no other pass ran in between — put it back at the
                    # front and stay parked (resources held) until the
                    # retiring holder's process exits and frees the IDs.
                    task.parked_node = node_id
                    q.appendleft(task)
                    break
            if not q:
                self.node_parked.pop(node_id, None)

    def _unpark(self, task: TaskRecord, release: bool = True):
        """Pull a task out of its node's parked queue (cancable/stale paths),
        optionally releasing the committed resources."""
        node_id = task.parked_node
        if node_id is None:
            return
        task.parked_node = None
        q = self.node_parked.get(node_id)
        if q is not None:
            try:
                q.remove(task)
            except ValueError:
                pass
        if release:
            self.scheduler.release(node_id, task.resources, task.strategy)

    def _try_reschedule_bundles(self):
        for pg_id in list(self.pgs_needing_bundles):
            if self.scheduler.reschedule_lost_bundles(pg_id):
                self.pgs_needing_bundles.discard(pg_id)

    @staticmethod
    def _needs_chip_grant(task: TaskRecord) -> bool:
        # Actor METHOD tasks run in the actor's process, which got its grant
        # at creation.  Fractional (<1) requests are admission-only time
        # sharing: no visibility isolation (two processes cannot map the
        # same chip concurrently anyway).
        return (int(task.resources.get("TPU", 0)) >= 1
                and not task.is_actor_task)

    def _find_idle_worker(
        self, node_id: NodeID, fresh: bool = False
    ) -> Optional[WorkerState]:
        for w in self.workers.values():
            if w.node_id == node_id and w.state == IDLE and w.conn.alive \
                    and not (fresh and w.used):
                return w
        return None

    def _maybe_spawn(self, node_id: NodeID, force: bool = False):
        cap = self.node_worker_caps.get(node_id, 0)
        # Actor-dedicated workers don't count against the task-worker pool cap
        # (reference: worker_pool.h tracks dedicated vs shared workers).
        count = 0
        blocked = 0
        for w in self.workers.values():
            if w.node_id != node_id:
                continue
            if w.state in (STARTING, IDLE, LEASED):
                count += 1
            elif w.state in (BLOCKED, DIRECT):
                # Direct-leased workers are spoken for by a client's lease,
                # not by this pool: like blocked workers, each permits one
                # extra spawn (else a driver leasing the whole pool would
                # starve head-scheduled tasks of processes), bounded by the
                # same hard cap.
                blocked += 1
        pending = self._spawn_pending.get(node_id, 0)
        # Blocked workers each permit one extra pool slot (their task's
        # resources were released), but total live processes are hard-capped
        # so a deeply nested get chain can't fork without bound.
        hard_cap = max(cap, 1) * self.config.worker_pool_hard_cap_multiple
        if count + blocked + pending >= hard_cap:
            return
        if count + pending < cap:
            self._spawn_worker(node_id)
            return
        if force:
            # Actor-creation tasks get dedicated processes: spawn one per
            # parked creation so a burst of actors starts in parallel instead
            # of one process per spawn-roundtrip (reference: worker_pool.h
            # maximum_startup_concurrency governs parallel worker startup).
            # `current_parked`: the caller's task is already in node_parked
            # (_drain_parked) or about to be parked (_dispatch_loop) — count
            # it exactly once either way.
            parked_creations = sum(
                1 for t in self.node_parked.get(node_id, ())
                if t.spec.get("is_actor_creation")
                or self._needs_chip_grant(t)
            )
            needed = max(parked_creations, 1)
            for _ in range(min(needed - pending,
                               hard_cap - (count + blocked + pending))):
                self._spawn_worker(node_id)

    async def _dispatch(self, task: TaskRecord, worker: WorkerState) -> bool:
        # Tasks that hold scheduler resources and request whole chips get
        # concrete chip IDs so the worker can isolate the TPU view
        # (reference: tpu.py:155 TPU_VISIBLE_CHIPS assignment at task start).
        # No IDs free (a blocked chip-holder released its float but its
        # process still maps the devices): refuse to dispatch — running the
        # task without a grant would silently compute on CPU.
        n_tpu = int(task.resources.get("TPU", 0))
        if n_tpu >= 1 and not task.is_actor_task:
            task.tpu_chips = self.scheduler.allocate_tpu_chips(
                worker.node_id, n_tpu
            )
            if task.tpu_chips is None:
                return False
            worker.tpu_chips.extend(task.tpu_chips)
            task.spec["tpu_chips"] = task.tpu_chips
        else:
            task.spec.pop("tpu_chips", None)
        task.state = RUNNING
        task.worker_id = worker.worker_id
        task.node_id = worker.node_id
        worker.used = True
        # Scheduling latency counts only up to the FIRST dispatch: a retry
        # after a worker death would otherwise fold the failed attempt's
        # execution time into the histogram.
        if task.start_time == 0.0:
            self.builtin_metrics.submit_to_start.observe(
                max(0.0, time.time() - task.submit_time))
        task.start_time = time.time()
        self.builtin_metrics.tasks_dispatched.inc()
        worker.last_seen = time.monotonic()
        is_actor_creation = task.spec.get("is_actor_creation", False)
        worker.state = ACTOR if is_actor_creation else LEASED
        worker.inflight.add(task.task_id)
        self._task_transition(task, "RUNNING")
        self._event("task_dispatched", task=task.task_id.hex(),
                    worker=worker.worker_id.hex())
        if is_actor_creation:
            actor_id = ActorID(task.spec["actor_id"])
            actor = self.actors[actor_id]
            actor.worker_id = worker.worker_id
            actor.node_id = worker.node_id
            worker.actor_id = actor_id
            # Log-index linkage: `ray_tpu logs <actor_id>` resolves to the
            # hosting worker's file (retained after the actor dies).
            log_entry = self.log_index.get(worker.worker_id.hex())
            if log_entry is not None:
                log_entry["actor_id"] = actor_id.hex()
        await worker.conn.push("execute_task", task.spec)
        return True

    async def h_task_done(self, conn, body):
        task_id = TaskID(body["task_id"])
        task = self.tasks.get(task_id)
        worker_id = self.conn_to_worker.get(conn.conn_id)
        worker = self.workers.get(worker_id) if worker_id else None
        if task is None:
            # Unknown task: either a stale duplicate, or a completion that
            # outlived a HEAD RESTART (the worker kept executing headless
            # and replayed the report after resync — the task record died
            # with the old head).  Only the restart case may seal: the
            # resync grace window is the discriminator.  A same-head blip
            # replay (task already requeued, run elsewhere, maybe freed)
            # must be DROPPED — sealing would resurrect freed records with
            # a ref nothing owns.
            if time.monotonic() < self._resync_grace_until:
                self._seal_orphan_returns(body, worker)
            return {}
        failed = body.get("error") is not None
        actor_creation = task.spec.get("is_actor_creation", False)

        # Application-level retryable error: resubmit.
        if failed and task.retries_left != 0 and body.get("retryable", False):
            task.retries_left -= 1
            task.state = PENDING
            self._release_task_resources(task, worker)
            self._task_transition(task, "RETRYING",
                                  error=body.get("error_repr", ""))
            task.worker_id = None
            task.node_id = None
            if task.is_actor_task:
                actor = self.actors.get(ActorID(task.spec["actor_id"]))
                if actor is not None and actor.state != "DEAD":
                    actor.pending_tasks.appendleft(task)
                    if actor.state == "ALIVE":
                        await self._drain_actor_queue(actor)
                    return {}
                # fall through: actor gone, give up and record the failure
                task.retries_left = 0
            else:
                self._enqueue_task(task)
                self._kick()
                return {}

        task.state = FAILED if failed else FINISHED
        task.end_time = time.time()
        if failed:
            task.error = body.get("error_repr", "")
            self._task_transition(
                task, FAILED, error=task.error,
                traceback_text=body.get("error_tb")
                or body.get("error_repr", ""),
            )
        else:
            self._task_transition(task, FINISHED)
        for ret in body.get("returns", []):
            oid = ObjectID(ret["object_id"])
            if task.spec.get("_reconstruct") and oid not in self.objects:
                # A freed sibling recomputed during reconstruction: nobody
                # references it — drop the stored copy instead of
                # resurrecting the record (mirrors the from_pull guard).
                if not failed and ret.get("inline") is None and worker:
                    self._adopt_local(oid, worker.node_id)
                    if worker.node_id == self.local_node_id:
                        self.store.free(oid)
                    else:
                        daemon = self.node_daemons.get(worker.node_id)
                        if daemon is not None:
                            asyncio.ensure_future(daemon.push(
                                "free_objects", {"object_ids": [ret["object_id"]]}
                            ))
                continue
            rec = self._obj(oid)
            if failed:
                if rec.sealed and (rec.inline is not None or rec.locations):
                    # A live sibling a reconstruction re-run didn't need:
                    # the failure must not clobber its valid data.
                    continue
                rec.error = body["error"]
            elif ret.get("inline") is not None:
                rec.error = None  # e.g. re-sealed by a restarted actor creation
                rec.inline = ret["inline"]
                rec.size = len(rec.inline)
            else:
                rec.error = None
                rec.size = ret["size"]
                loc = worker.node_id if worker else self.local_node_id
                rec.locations.add(loc)
                self._adopt_local(oid, loc)
            rec.sealed = True
            self._notify_object_ready(oid)
        if task.spec.get("num_returns") == "streaming":
            self.stream_done[task_id] = body.get("stream_count", 0)
            for key, evs in list(self.stream_waiters.items()):
                if key[0] == task_id.binary():
                    for ev in self.stream_waiters.pop(key):
                        ev.set()
        self._event("task_done", task=task_id.hex(), failed=failed)

        if actor_creation:
            actor_id = ActorID(task.spec["actor_id"])
            actor = self.actors.get(actor_id)
            if actor:
                if failed:
                    actor.state = "DEAD"
                    self._mark_dirty()  # drop from the snapshot
                    actor.death_cause = body.get("error_repr", "creation failed")
                    await self._fail_actor_queue(actor, body.get("error"))
                    await self._publish_actor_event(actor, "DEAD")
                    if worker:
                        worker.state = IDLE
                        worker.actor_id = None
                else:
                    actor.state = "ALIVE"
                    await self._publish(
                        f"actor:{actor_id.hex()}", {"state": "ALIVE"}
                    )
                    # Route broadcast with the hosting worker's peer
                    # address: creating clients pre-dial during creation
                    # dispatch (no first-call handshake cliff).
                    await self._publish_actor_event(actor, "ALIVE")
                    await self._drain_actor_queue(actor)
            self._release_task_resources(task, worker, keep_worker_busy=not failed)
        elif task.spec.get("actor_id"):
            actor = self.actors.get(ActorID(task.spec["actor_id"]))
            if actor:
                actor.num_executed += 1
            self._release_task_resources(task, worker, keep_worker_busy=True)
        else:
            self._release_task_resources(task, worker)
        self._finalize_task(task)
        self._kick()
        return {}

    def _seal_orphan_returns(self, body, worker: Optional[WorkerState]):
        """Seal return objects of a task this head has no record of (a
        completion replayed across a head restart).  Only objects someone
        can still reach matter, but the creator's ref is alive by
        construction (the submitting driver survived the head, or the
        report wouldn't have been replayed) — so register unconditionally;
        the creator's eventual free reclaims the record."""
        returns = body.get("returns") or []
        if not returns:
            return
        failed = body.get("error") is not None
        sealed = 0
        for ret in returns:
            oid = ObjectID(ret["object_id"])
            rec = self._obj(oid)
            if failed:
                if rec.sealed and (rec.inline is not None or rec.locations):
                    continue  # never clobber live data with a late failure
                rec.error = body["error"]
            elif ret.get("inline") is not None:
                rec.error = None
                rec.inline = ret["inline"]
                rec.size = len(rec.inline)
            elif ret.get("size") is not None:
                rec.error = None
                rec.size = ret["size"]
                loc = worker.node_id if worker else self.local_node_id
                rec.locations.add(loc)
                self._adopt_local(oid, loc)
            else:
                continue
            rec.sealed = True
            sealed += 1
            self._notify_object_ready(oid)
        if sealed:
            self._event("task_done", task=TaskID(body["task_id"]).hex(),
                        failed=failed, orphan=True)

    def _retire_worker(self, worker: WorkerState):
        """Tell a chip-granted pooled worker to exit: its process keeps the
        TPU devices mapped, so the chip IDs only become reusable at process
        death (reference: raylet kills GPU workers whose CUDA_VISIBLE_DEVICES
        grant must be reclaimed rather than re-leasing the process)."""
        if worker.state in (DEAD, RETIRING):
            return
        worker.state = RETIRING
        if worker.conn.alive:
            async def _push_exit():
                try:
                    await worker.conn.push("exit", {})
                except Exception:
                    pass  # racing the SIGTERM below is expected

            asyncio.ensure_future(_push_exit())
        if worker.node_id == self.local_node_id:
            # Belt and braces for wedged processes; remote nodes reap via
            # their daemon when the connection drops.
            try:
                os.kill(worker.pid, 15)
            except (ProcessLookupError, PermissionError):
                pass

    def _release_task_resources(self, task, worker, keep_worker_busy=False):
        if task.is_actor_task:
            release = False  # actor method tasks hold no scheduler resources
        elif task.spec.get("is_actor_creation"):
            # A live actor keeps its creation resources until death.
            release = task.state in (FAILED, PENDING)
        else:
            release = True
        # A task still flagged blocked already released its resources in
        # h_task_blocked (e.g. its unblock RPC was lost).
        if release and task.node_id is not None and not task.blocked:
            self.scheduler.release(task.node_id, task.resources, task.strategy)
        if release and task.tpu_chips and worker is not None:
            # The worker ran with a chip grant; the grant dies with the
            # process (chips freed in _handle_worker_death).
            self._retire_worker(worker)
        task.blocked = False
        if worker:
            worker.inflight.discard(task.task_id)
            worker.last_seen = time.monotonic()
            if not keep_worker_busy and worker.state not in (RETIRING, DEAD):
                worker.state = IDLE

    # -- blocked workers (reference: raylet releases the CPU lease while a
    # worker blocks in ray.get; worker_pool.h spawns past the cap for it) ----

    async def h_task_blocked(self, conn, body):
        worker_id = self.conn_to_worker.get(conn.conn_id)
        worker = self.workers.get(worker_id) if worker_id else None
        task = self.tasks.get(TaskID(body["task_id"]))
        if (task is None or worker is None or task.blocked
                or task.state != RUNNING or worker.state != LEASED
                or task.is_actor_task):
            return {}
        task.blocked = True
        worker.state = BLOCKED
        self.scheduler.release(task.node_id, task.resources, task.strategy)
        self._kick()  # freed resources may unblock queued tasks
        return {}

    async def h_task_unblocked(self, conn, body):
        worker_id = self.conn_to_worker.get(conn.conn_id)
        worker = self.workers.get(worker_id) if worker_id else None
        task = self.tasks.get(TaskID(body["task_id"]))
        if task is None or not task.blocked:
            return {}
        task.blocked = False
        if worker is not None and worker.state == BLOCKED:
            worker.state = LEASED
        # Oversubscribes transiently if the freed resources were re-used;
        # self-corrects as running tasks finish.
        self.scheduler.acquire_force(task.node_id, task.resources, task.strategy)
        return {}

    async def h_health_ack(self, conn, body):
        worker_id = self.conn_to_worker.get(conn.conn_id)
        w = self.workers.get(worker_id) if worker_id else None
        if w is not None:
            w.last_ack = time.monotonic()
        return {}

    async def h_span_batch(self, conn, body):
        """Batched finished tracing spans from any process -> timeline
        ring (reference: task events flow to GcsTaskManager via
        task_event_buffer.h in batches; `ray timeline` reads them back).
        One RPC carries a whole ring flush — the span plane never pays a
        head dispatch per span; malformed entries are skipped so one bad
        emitter can't drop a process's whole batch."""
        for span in body["spans"]:
            if not isinstance(span, dict) or not span.get("trace_id") \
                    or not span.get("span_id"):
                continue
            self._event("span", **{k: span.get(k) for k in (
                "trace_id", "span_id", "parent_id", "name", "start", "end",
                "pid", "attrs",
            )})
            # Task execution spans feed the built-in duration histogram —
            # the trace↔metrics link: the same span that draws the
            # timeline bar contributes to ray_tpu_task_duration_seconds.
            start, end = span.get("start"), span.get("end")
            if (str(span.get("name", "")).startswith("task:")
                    and isinstance(start, (int, float))
                    and isinstance(end, (int, float)) and end >= start):
                self.builtin_metrics.task_duration.observe(end - start)
        return {}

    async def h_engine_step_batch(self, conn, body):
        """Batched flight-recorder step records from inference engines
        (util/steprec ring flush, riding the same coalesced-batch path as
        span_batch/task_done).  Per-engine bounded rings: the head keeps
        the recent window, the worker's black-box sidecar keeps the
        crash-proof copy.  Malformed entries are skipped so one bad
        record can't drop an engine's whole batch."""
        cap = max(16, self.config.engine_steps_max_records)
        for rec in body["steps"]:
            if not isinstance(rec, dict) or not rec.get("engine") \
                    or not isinstance(rec.get("step"), int):
                continue
            eid = str(rec["engine"])
            ring = self.engine_steps.get(eid)
            if ring is None:
                # Bound the engine table itself (worker churn must not
                # grow it forever): evict the least-recently-fed engine.
                while len(self.engine_steps) >= 64:
                    self.engine_steps.popitem(last=False)
                ring = self.engine_steps[eid] = deque(maxlen=cap)
            else:
                self.engine_steps.move_to_end(eid)
            ring.append(rec)
        return {}

    async def h_gang_round_batch(self, conn, body):
        """Batched gang round records (util/gangrec ring flush, the train
        session's per-rank flight recorder).  Joined by (gang, round):
        the moment a round holds a record from EVERY rank it collapses
        into one skew profile (gangrec.skew_profile) — which rank arrived
        last and which phase made it late — retained in a bounded
        per-gang ring for list_state("gang_rounds") / `ray_tpu gang` and
        the gang health detectors.  Malformed entries are skipped so one
        bad record can't drop a gang's whole batch."""
        from ..util import gangrec as _gangrec
        cap = max(16, self.config.gang_rounds_max_records)
        for rec in body["rounds"]:
            if not isinstance(rec, dict) or not rec.get("gang") \
                    or not isinstance(rec.get("round"), int) \
                    or not isinstance(rec.get("rank"), int):
                continue
            gid = str(rec["gang"])
            st = self.gang_rounds.get(gid)
            if st is None:
                # Bound the gang table itself (gang churn must not grow
                # it forever): evict the least-recently-fed gang.
                while len(self.gang_rounds) >= max(
                        1, self.config.gang_rounds_max_gangs):
                    self.gang_rounds.popitem(last=False)
                st = self.gang_rounds[gid] = {
                    "pending": OrderedDict(),  # round -> {rank: rec}
                    "profiles": deque(maxlen=cap),
                    "world": 0, "last_t": 0.0,
                    "latest_by_rank": {},
                }
            else:
                self.gang_rounds.move_to_end(gid)
            world = rec.get("world")
            if isinstance(world, int) and world > 0:
                st["world"] = world
            t = rec.get("t")
            if isinstance(t, (int, float)):
                st["last_t"] = max(st["last_t"], float(t))
            st["latest_by_rank"][rec["rank"]] = rec
            pend = st["pending"]
            rnd = pend.get(rec["round"])
            if rnd is None:
                # Bound the join buffer: a rank that died mid-round leaves
                # a forever-incomplete round behind — evict oldest-first.
                while len(pend) >= 64:
                    pend.popitem(last=False)
                rnd = pend[rec["round"]] = {}
            rnd[rec["rank"]] = rec
            if st["world"] and len(rnd) >= st["world"]:
                del pend[rec["round"]]
                prof = _gangrec.skew_profile(rnd)
                if prof is not None:
                    st["profiles"].append(prof)
                    self.builtin_metrics.gang_round_skew.observe(
                        prof["skew_s"])
        return {}

    async def h_devmem_report(self, conn, body):
        """Device-memory snapshot from a worker (util/devmem pools +
        per-device stats + compile observability), identity-joined here
        so list_state("devmem") / `ray_tpu top` can group by node."""
        pid = int(body["pid"])
        worker_id = self.conn_to_worker.get(conn.conn_id)
        w = self.workers.get(worker_id) if worker_id else None
        self.devmem_by_pid[pid] = {
            "pid": pid,
            "worker_id": worker_id.hex() if worker_id else None,
            "node_id": w.node_id.hex() if w is not None else None,
            "devmem": body["devmem"],
            "time": time.time(),
        }
        while len(self.devmem_by_pid) > 256:
            oldest = min(self.devmem_by_pid,
                         key=lambda p: self.devmem_by_pid[p]["time"])
            del self.devmem_by_pid[oldest]
        return {}

    async def h_node_stats(self, conn, body):
        node_id = NodeID(body["node_id"])
        self.node_stats[node_id] = {
            "store": body.get("store"),
            "load1": body.get("load1"),
            "mem_used_frac": body.get("mem_used_frac"),
            "num_worker_procs": body.get("num_worker_procs"),
            "headless_s": body.get("headless_s"),
            "time": time.time(),
        }
        if body.get("headless_s") is not None:
            self.builtin_metrics.headless_seconds.set(
                float(body["headless_s"]), tags={"node": node_id.hex()})
        return {}

    async def h_node_health_ack(self, conn, body):
        self.node_last_ack[NodeID(body["node_id"])] = time.monotonic()
        return {}

    async def h_node_drain(self, conn, body):
        """Announced preemption (spot/maintenance SIGTERM with a grace
        window): the node daemon reports DRAINING before it goes away.  The
        scheduler stops leasing onto the node immediately, and every
        subscribed process (train sessions subscribe at worker setup) gets a
        ``node_events`` drain notification so gangs can checkpoint inside
        the grace window (reference: GcsNodeManager DrainNode + the
        autoscaler's drain-before-terminate; TorchTitan-style graceful
        drain on SIGTERM)."""
        node_id = NodeID(body["node_id"])
        grace_s = float(body.get("grace_s", 0.0))
        marked = self.scheduler.mark_draining(node_id)
        self._event("node_drain", node=node_id.hex(), grace_s=grace_s)
        # Revoke the draining node's task leases: clients stop routing new
        # work there, in-flight specs drain inside the grace window, and
        # the slots' resources free for the exclusion accounting.
        for lease_id, lease in list(self.leases.items()):
            if lease["node_id"] == node_id:
                await self._revoke_lease(lease_id, "node_draining")
        await self._publish("node_events", {
            "event": "drain",
            "node_id": node_id.hex(),
            "grace_s": grace_s,
        })
        # Idle workers on a draining node have nothing to finish: shut them
        # down now so the daemon (which exits early once its last worker is
        # gone) doesn't sit out the full grace window for an idle node —
        # the autoscaler's scale-down path stays fast.  Leased/actor
        # workers keep running: they are what the grace window is FOR.
        for w in list(self.workers.values()):
            if w.node_id == node_id and w.state == IDLE and w.conn.alive:
                try:
                    await w.conn.push("shutdown", {})
                except Exception:
                    pass
        return {"draining": marked}

    async def h_stream_item(self, conn, body):
        task_id = body["task_id"]
        idx = body["index"]
        oid = ObjectID(body["object_id"])
        rec = self._obj(oid)
        worker_id = self.conn_to_worker.get(conn.conn_id)
        worker = self.workers.get(worker_id) if worker_id else None
        if body.get("inline") is not None:
            rec.inline = body["inline"]
            rec.size = len(rec.inline)
        else:
            rec.size = body["size"]
            loc = worker.node_id if worker else self.local_node_id
            rec.locations.add(loc)
            self._adopt_local(oid, loc)
        rec.sealed = True
        self.stream_items[(task_id, idx)] = {"object_id": body["object_id"]}
        for ev in self.stream_waiters.pop((task_id, idx), []):
            ev.set()
        self._notify_object_ready(oid)
        return {}

    async def h_next_stream_item(self, conn, body):
        task_id_raw = body["task_id"]
        idx = body["index"]
        key = (task_id_raw, idx)
        tid = TaskID(task_id_raw)
        while key not in self.stream_items:
            if tid in self.stream_done and idx >= self.stream_done[tid]:
                task = self.tasks.get(tid)
                if task and task.state == FAILED:
                    ret_ids = task.spec.get("return_ids") or []
                    if ret_ids:
                        rec = self.objects.get(ObjectID(ret_ids[0]))
                        if rec is not None and rec.error is not None:
                            return {"error": rec.error}
                return {"done": True}
            ev = asyncio.Event()
            self.stream_waiters.setdefault(key, []).append(ev)
            await ev.wait()
        return {"object_id": self.stream_items[key]["object_id"]}

    async def h_cancel_task(self, conn, body):
        task_id = TaskID(body["task_id"])
        task = self.tasks.get(task_id)
        if task is None:
            return {"cancelled": False}
        if task.state == PENDING:
            task.state = FAILED
            task.error = "cancelled"
            self._task_transition(task, FAILED, error="cancelled")
            err = serialization.pack(TaskCancelledError(task_id.hex()))
            for raw in task.spec.get("return_ids", []):
                rec = self._obj(ObjectID(raw))
                rec.error = err
                rec.sealed = True
                self._notify_object_ready(rec.object_id)
            try:
                self.queued_tasks.remove(task)
                self._dequeue_shape(task)
            except ValueError:
                pass
            self._unpark(task)  # releases node-committed resources, if any
            self._finalize_task(task)
            return {"cancelled": True}
        if task.state == RUNNING and task.worker_id:
            w = self.workers.get(task.worker_id)
            if w and w.conn.alive:
                await w.conn.push("cancel", {"task_id": body["task_id"],
                                             "force": body.get("force", False)})
                return {"cancelled": True}
        return {"cancelled": False}

    # -- actors ----------------------------------------------------------------

    async def h_create_actor(self, conn, body):
        actor_id = ActorID(body["actor_id"])
        actor = ActorRecord(actor_id, body)
        if actor.name:
            if actor.name in self.named_actors:
                raise ValueError(f"actor name {actor.name!r} already taken")
            self.named_actors[actor.name] = actor_id
            # A fresh creation supersedes any restart-loss tombstone.
            self.named_tombstones.pop(actor.name, None)
            self._mark_dirty()
        # Stamp the actor-level metadata into the creation task the worker
        # will receive and RETAIN: it is the worker's field-state report
        # after a head restart, and the restarted head rebuilds this exact
        # ActorRecord from it (see _resync_worker_adopt).
        body["creation_task"]["actor_meta"] = {
            k: body.get(k)
            for k in ("class_name", "name", "namespace", "max_restarts",
                      "max_task_retries", "method_names", "method_defaults",
                      "lifetime")
        }
        self.actors[actor_id] = actor
        await self.h_submit_task(conn, body["creation_task"])
        return {}

    async def _drain_parked_unknown_actor_tasks(self, force: bool = False):
        """Re-run parked unknown-actor submissions whose actor is now
        known (adoption or snapshot replay landed).  With ``force`` (grace
        window closed) everything re-runs — still-unknown actors then take
        the normal typed ActorDiedError path."""
        if not self._parked_unknown_actor_tasks:
            return
        parked, self._parked_unknown_actor_tasks = \
            self._parked_unknown_actor_tasks, []
        keep: List[dict] = []
        for body in parked:
            if force or ActorID(body["actor_id"]) in self.actors:
                try:
                    await self.h_submit_actor_task(None, body)
                except Exception:
                    pass
            else:
                keep.append(body)
        # Preserve arrival order for specs still waiting on their adoption
        # (anything parked by the re-runs above lands after them, which
        # matches submission order per actor).
        self._parked_unknown_actor_tasks[:0] = keep

    async def h_submit_actor_task(self, conn, body):
        actor_id = ActorID(body["actor_id"])
        actor = self.actors.get(actor_id)
        if actor is None and time.monotonic() < self._resync_grace_until:
            # Head-restart resync race: a reconnected driver's buffered
            # submissions can replay BEFORE the hosting worker's field
            # report adopts the actor.  Park the spec for the grace window;
            # adoption (or named replay) drains it, expiry fails it typed.
            self._parked_unknown_actor_tasks.append(body)
            return {}
        if actor is None or actor.state == "DEAD":
            err = serialization.pack(
                ActorDiedError(actor_id.hex(), actor.death_cause if actor else "unknown actor")
            )
            for raw in body.get("return_ids", []):
                rec = self._obj(ObjectID(raw))
                rec.error = err
                rec.sealed = True
                self._notify_object_ready(rec.object_id)
            return {}
        task = TaskRecord(body)
        self._register_task(task)
        self._task_transition(task, "SUBMITTED")
        # Strict per-actor FIFO: anything already queued keeps its place
        # (reference: sequential_actor_submit_queue.h).
        if actor.state != "ALIVE" or task.pending_deps or actor.pending_tasks:
            actor.pending_tasks.append(task)
            if actor.state == "ALIVE":
                await self._drain_actor_queue(actor)
            return {}
        await self._push_actor_task(actor, task)
        return {}

    async def _push_actor_task(
        self, actor: ActorRecord, task: TaskRecord
    ) -> bool:
        """Dispatch one task to the actor's worker.  Returns False when the
        task could not be dispatched now: re-queued (worker gone, actor
        restarting) or terminally failed (actor DEAD) — callers draining a
        queue must stop on False instead of spinning."""
        if task.state != PENDING:  # e.g. cancelled while queued
            return True
        if actor.state == "DEAD":
            # The death handler already failed whatever was queued at the
            # time; a task resurfacing later (e.g. a drain snapshot that
            # raced the death) must fail the same way, never be orphaned on
            # a queue nothing will drain again.
            actor.pending_tasks.append(task)
            await self._fail_actor_queue(actor, None)
            return False
        worker = self.workers.get(actor.worker_id)
        if worker is None or not worker.conn.alive:
            # Back to the FRONT: the FIFO drain popped this task from the
            # head of the queue, and a tail re-append would reorder it
            # behind later submissions across a restart.
            actor.pending_tasks.appendleft(task)
            return False
        task.state = RUNNING
        task.worker_id = worker.worker_id
        task.node_id = worker.node_id
        worker.used = True
        if task.start_time == 0.0:  # first dispatch only (see _dispatch)
            self.builtin_metrics.submit_to_start.observe(
                max(0.0, time.time() - task.submit_time))
        task.start_time = time.time()
        self.builtin_metrics.tasks_dispatched.inc()
        worker.inflight.add(task.task_id)
        self._task_transition(task, "RUNNING")
        await worker.conn.push("execute_task", task.spec)
        return True

    async def _drain_actor_queue(self, actor: ActorRecord):
        if (actor.spec.get("creation_task") or {}).get(
                "execute_out_of_order"):
            # Out-of-order submit queue: dependency-READY tasks dispatch
            # past dep-blocked ones; relative order among ready tasks is
            # preserved (reference: out_of_order_actor_submit_queue.h —
            # dispatch reordering only; the worker still bounds execution
            # concurrency by max_concurrency).
            ready = [t for t in actor.pending_tasks
                     if t.state == PENDING and not t.pending_deps]
            # Replace the queue BEFORE awaiting: _push_actor_task may
            # re-append (dead worker), and new submissions may land
            # mid-await — both must go to the live deque, not a snapshot.
            actor.pending_tasks = deque(
                t for t in actor.pending_tasks
                if t.state == PENDING and t.pending_deps)
            for i, task in enumerate(ready):
                if not await self._push_actor_task(actor, task):
                    # Worker vanished mid-drain: requeue the untried rest
                    # (the failed one was already re-appended or failed).
                    actor.pending_tasks.extend(ready[i + 1:])
                    if actor.state == "DEAD":
                        # The death handler's queue-fail already ran; these
                        # stragglers must fail too, not sit orphaned.
                        await self._fail_actor_queue(actor, None)
                    return
            return
        while actor.pending_tasks:
            task = actor.pending_tasks[0]
            if task.state != PENDING:  # cancelled: drop and move on
                actor.pending_tasks.popleft()
                continue
            if task.pending_deps:
                break  # FIFO order: a dep-blocked head blocks the queue
            actor.pending_tasks.popleft()
            if not await self._push_actor_task(actor, task):
                # Not dispatchable now (worker died / actor DEAD): the task
                # is back on the queue or failed.  Stop — looping again
                # would pop and re-append the same head in a tight,
                # never-yielding spin that starves the event loop (incl.
                # the death handler that would break the cycle).
                break

    async def _fail_actor_queue(self, actor: ActorRecord, error: Optional[bytes]):
        err = error or serialization.pack(
            ActorDiedError(actor.actor_id.hex(), actor.death_cause or "actor died")
        )
        while actor.pending_tasks:
            task = actor.pending_tasks.popleft()
            task.state = FAILED
            for raw in task.spec.get("return_ids", []):
                rec = self._obj(ObjectID(raw))
                rec.error = err
                rec.sealed = True
                self._notify_object_ready(rec.object_id)

    async def h_kill_actor(self, conn, body):
        actor_id = ActorID(body["actor_id"])
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"killed": False}
        if body.get("no_restart", True):
            actor.restarts_left = 0
        worker = self.workers.get(actor.worker_id) if actor.worker_id else None
        if worker is not None and worker.conn.alive:
            # Push-based kill: works across nodes (the worker's RPC thread
            # calls os._exit even if the main thread is busy).  Local workers
            # also get a SIGKILL in case the process is wedged.
            try:
                await worker.conn.push("exit", {})
            except Exception:
                pass
            if worker.node_id == self.local_node_id:
                try:
                    os.kill(worker.pid, 9)
                except (ProcessLookupError, PermissionError):
                    pass
            # The worker is doomed by OUR signal — process the death now
            # instead of waiting for the connection EOF.  Otherwise a
            # direct-call client whose peer connection broke first
            # re-submits the in-flight call (retry budget already charged)
            # and the resubmission races the EOF: dispatched to the
            # still-registered dead worker, it dies with retries_left=0.
            # The later EOF-driven death handler no-ops (worker popped).
            await self._handle_worker_death(worker.worker_id)
        else:
            if actor.state != "DEAD":
                actor.state = "DEAD"
                self._mark_dirty()  # drop from the snapshot
                actor.death_cause = "killed via kill_actor"
                if actor.name:
                    self.named_actors.pop(actor.name, None)
                await self._publish_actor_event(actor, "DEAD")
                await self._fail_actor_queue(actor, None)
                self._free_actor_creation_args(actor)
        return {"killed": True}

    async def h_worker_ready(self, conn, body):
        worker_id = self.conn_to_worker.get(conn.conn_id)
        w = self.workers.get(worker_id) if worker_id else None
        if w is not None and w.state == STARTING:
            w.state = IDLE
            self._kick()
        return {}

    async def h_get_actor_by_name(self, conn, body):
        actor_id = self.named_actors.get(body["name"])
        if actor_id is None:
            reply = {"found": False}
            tomb = self.named_tombstones.get(body["name"])
            if tomb:
                reply["tombstone"] = tomb
            return reply
        actor = self.actors[actor_id]
        return {
            "found": True,
            "actor_id": actor_id.binary(),
            "spec": {
                k: actor.spec.get(k)
                for k in ("class_name", "method_names", "max_task_retries",
                          "method_defaults")
            },
        }

    async def h_list_named_actors(self, conn, body):
        return {"names": sorted(self.named_actors)}

    # -- dataplane: direct actor calls + node-local task leases ---------------
    # (reference: Ray's core workers submit actor tasks directly to each
    # other and lease execution slots from the per-node raylet so
    # steady-state submission never touches the GCS — core_worker.proto
    # PushTask, node_manager.proto RequestWorkerLease.  The head stays the
    # lessor and the address directory; the per-call traffic moves to the
    # workers' peer servers.)

    def _actor_route_wire(self, actor: ActorRecord) -> Optional[dict]:
        """Peer-route descriptor for an ALIVE actor's hosting worker, or
        None when the worker has no reachable peer server."""
        worker = self.workers.get(actor.worker_id) if actor.worker_id else None
        if worker is None or not worker.conn.alive or not worker.peer_addr:
            return None
        return {
            "addr": worker.peer_addr,
            "worker_id": worker.worker_id.binary(),
            "node_id": worker.node_id.binary(),
            "session": self.node_sessions.get(worker.node_id, self.session),
            # Object-plane endpoints of the worker's node: direct-result
            # descriptors stamp these so cross-node readers can pull
            # without a directory lookup.
            "object_addr": self.node_object_addrs.get(worker.node_id),
            "bulk_addr": self.node_bulk_addrs.get(worker.node_id),
        }

    async def h_resolve_actor(self, conn, body):
        """Address resolution for direct actor calls.  `busy` reports
        whether the actor has head-queued or in-flight tasks: a client that
        already routed calls through the head must not switch to the peer
        plane while any could still be ahead (per-submitter FIFO has to
        survive the switch); a client with no prior traffic to this actor
        may dial regardless of other submitters."""
        actor = self.actors.get(ActorID(body["actor_id"]))
        if actor is None or actor.state == "DEAD":
            return {"ready": False, "dead": True}
        if (actor.spec.get("creation_task") or {}).get("execute_out_of_order"):
            # Out-of-order dispatch is a head-side reordering feature; a
            # FIFO peer connection cannot express it.
            return {"ready": False, "unsupported": True}
        if actor.state != "ALIVE":
            return {"ready": False}
        route = self._actor_route_wire(actor)
        if route is None:
            return {"ready": False}
        worker = self.workers[actor.worker_id]
        busy = bool(actor.pending_tasks) or bool(worker.inflight)
        return {"ready": True, "busy": busy, **route}

    async def _publish_actor_event(self, actor: ActorRecord, state: str):
        """Actor lifecycle broadcast for client route caches: ALIVE carries
        the peer route (pre-warm — subscribers dial during creation
        dispatch instead of paying the handshake on the first call);
        RESTARTING/DEAD invalidate cached addresses."""
        data = {"actor_id": actor.actor_id.hex(), "state": state}
        if state == "ALIVE":
            route = self._actor_route_wire(actor)
            if route is not None:
                data.update(route)
        await self._publish("actor_events", data)

    async def h_direct_done(self, conn, body):
        """Batched completion report for a directly-executed task (peer
        actor call or leased submission): keeps the task history, the
        timeline, and actor accounting complete without per-call head
        dispatch.  Return-object registration rides the submitter's put
        batch, not this report."""
        task_id = TaskID(body["task_id"])
        failed = bool(body.get("failed"))
        state = FAILED if failed else FINISHED
        cap = self.config.task_history_max_tasks
        worker_id = self.conn_to_worker.get(conn.conn_id)
        if cap > 0:
            hexid = task_id.hex()
            rec = self.task_history.get(hexid)
            if rec is None:
                rec = self.task_history[hexid] = {
                    "task_id": hexid,
                    "name": body.get("name", ""),
                    "actor_id": (ActorID(body["actor_id"]).hex()
                                 if body.get("actor_id") else None),
                    "state": state,
                    "node_id": None,
                    "worker_id": None,
                    "error": None,
                    "traceback": None,
                    "events": [],
                }
                while len(self.task_history) > cap:
                    self.task_history.popitem(last=False)
            ev: Dict[str, Any] = {"state": state,
                                  "ts": body.get("end") or time.time(),
                                  "direct": True}
            if worker_id is not None:
                rec["worker_id"] = ev["worker"] = worker_id.hex()
                w = self.workers.get(worker_id)
                if w is not None:
                    rec["node_id"] = ev["node"] = w.node_id.hex()
            if failed:
                rec["error"] = ev["error"] = body.get("error_repr", "")
                rec["traceback"] = (body.get("error_tb")
                                    or body.get("error_repr", ""))
            rec["state"] = state
            rec["events"].append(ev)
            if len(rec["events"]) > self.config.task_history_max_events:
                del rec["events"][1]
        self.finished_tasks.append({
            "task_id": task_id.hex(),
            "name": body.get("name", ""),
            "state": state,
            "start_time": body.get("start", 0.0),
            "end_time": body.get("end", 0.0),
            "error": body.get("error_repr") if failed else None,
        })
        self._event("task_done", task=task_id.hex(), failed=failed,
                    direct=True)
        if body.get("actor_id"):
            actor = self.actors.get(ActorID(body["actor_id"]))
            if actor is not None and not failed:
                actor.num_executed += 1
        if worker_id is not None:
            w = self.workers.get(worker_id)
            if w is not None:
                w.last_seen = time.monotonic()
        return {}

    async def h_lease_request(self, conn, body):
        """Grant direct-submission slots: idle peer-reachable workers whose
        node can hold the shape's resources.  Never grants while the head
        itself has unplaced work — leased-out capacity must not starve
        queued tasks or pending gangs.  Scheduler invariants hold because a
        slot IS a resource acquisition (scheduler.lease_slot), released at
        return/revoke/disconnect."""
        cfg = self.config
        resources = {k: float(v)
                     for k, v in (body.get("resources") or {}).items()}
        count = max(0, min(int(body.get("count", 1)), cfg.lease_max_slots))
        slots: List[dict] = []
        starved = bool(self.pending_pgs) or any(
            q for q in self.node_parked.values())
        if not starved and self.queued_tasks:
            # Queued head work only blocks grants once it has genuinely
            # waited (a burst's own in-flight submissions must not deny
            # the lease that would carry the next burst).
            starved = max(
                (time.time() - t.submit_time for t in self.queued_tasks
                 if t.state == PENDING), default=0.0) > 0.25
        if not starved and int(resources.get("TPU", 0)) < 1:
            now = time.monotonic()
            # Fairness: one cold client must not vacuum the whole idle pool
            # in a single grant (multi-client warm-up would starve the
            # rest onto the head path) — leave half the idle workers for
            # other requesters; growth requests can take more later.
            n_idle = sum(1 for w in self.workers.values()
                         if w.state == IDLE and w.conn.alive and w.peer_addr)
            count = min(count, max(1, n_idle // 2)) if n_idle else 0
            for w in self.workers.values():
                if len(slots) >= count:
                    break
                if w.state != IDLE or not w.conn.alive or not w.peer_addr:
                    continue
                if not self.scheduler.lease_slot(w.node_id, resources):  # rt-owns: sched_slot
                    continue
                lease_id = os.urandom(8)
                self.leases[lease_id] = {
                    "worker_id": w.worker_id,
                    "node_id": w.node_id,
                    "conn_id": conn.conn_id,
                    "resources": resources,
                    "expires": now + cfg.lease_ttl_s,
                    "revoke_deadline": None,
                }
                w.state = DIRECT
                w.used = True
                w.last_seen = now
                slots.append({
                    "lease_id": lease_id,
                    "worker_id": w.worker_id.binary(),
                    "node_id": w.node_id.binary(),
                    "addr": w.peer_addr,
                    "session": self.node_sessions.get(w.node_id,
                                                      self.session),
                    "object_addr": self.node_object_addrs.get(w.node_id),
                    "bulk_addr": self.node_bulk_addrs.get(w.node_id),
                })
        return {"slots": slots, "ttl_s": cfg.lease_ttl_s}

    def _finalize_lease(self, lease_id: bytes, reason: str,
                        revoked: bool = False):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        self.scheduler.release_slot(lease["node_id"], lease["resources"])
        w = self.workers.get(lease["worker_id"])
        if w is not None and w.state == DIRECT:
            w.state = IDLE
            w.last_seen = time.monotonic()
        if revoked:
            self.builtin_metrics.lease_revocations.inc(
                tags={"reason": reason})
        self._kick()

    async def _revoke_lease(self, lease_id: bytes, reason: str):
        """Ask the owner to stop using (and return) a lease; force-reclaim
        after a short deadline so a wedged client can't pin the slot.
        The grant only frees at lease_return (or the deadline): in-flight
        specs already pipelined to the worker drain first."""
        lease = self.leases.get(lease_id)
        if lease is None or lease["revoke_deadline"] is not None:
            return
        lease["revoke_deadline"] = time.monotonic() + 2.0
        self._event("lease_revoke", lease=lease_id.hex(), reason=reason)
        c = self.server.connections.get(lease["conn_id"])
        if c is None:
            self._finalize_lease(lease_id, reason, revoked=True)
            return
        try:
            await c.push("lease_revoke",
                         {"lease_id": lease_id, "reason": reason})
        except Exception:
            self._finalize_lease(lease_id, reason, revoked=True)

    async def h_lease_return(self, conn, body):
        for raw in body.get("lease_ids", []):
            lease = self.leases.get(bytes(raw))
            # Only the owner returns a lease: a confused client must not
            # release someone else's slot.
            if lease is not None and lease["conn_id"] == conn.conn_id:
                revoked = lease["revoke_deadline"] is not None
                self._finalize_lease(bytes(raw), "revoked" if revoked
                                     else "returned", revoked=revoked)
        return {}

    async def h_lease_renew(self, conn, body):
        now = time.monotonic()
        for raw in body.get("lease_ids", []):
            lease = self.leases.get(bytes(raw))
            if lease is not None and lease["conn_id"] == conn.conn_id \
                    and lease["revoke_deadline"] is None:
                lease["expires"] = now + self.config.lease_ttl_s
        return {}

    # -- worker death / fault tolerance ---------------------------------------

    async def _handle_worker_death(self, worker_id: WorkerID):
        worker = self.workers.pop(worker_id, None)
        if worker is None:
            return
        worker.state = DEAD
        self._event("worker_died", worker=worker_id.hex(),
                    actor=worker.actor_id.hex() if worker.actor_id else None,
                    inflight=len(worker.inflight))
        # A leased slot dies with its worker: release the resources now and
        # tell the owner so it drops the slot (its in-flight specs fail on
        # the peer connection and fall back to the head path).
        for lease_id, lease in list(self.leases.items()):
            if lease["worker_id"] == worker_id:
                c = self.server.connections.get(lease["conn_id"])
                self._finalize_lease(lease_id, "worker_died", revoked=True)
                if c is not None:
                    try:
                        await c.push("lease_revoke", {
                            "lease_id": lease_id, "reason": "worker_died",
                        })
                    except Exception:
                        pass
        self._log_mark_dead(worker_id.hex())
        oom_killed = self._oom_kills.pop(worker_id, None) is not None
        self.node_worker_counts[worker.node_id] = max(
            0, self.node_worker_counts.get(worker.node_id, 1) - 1
        )
        if worker.tpu_chips:
            # The process is gone, so its TPU devices are actually free now.
            self.scheduler.free_tpu_chips(worker.node_id, worker.tpu_chips)
            worker.tpu_chips = []
            self._kick()  # chip-starved parked tasks can dispatch
        # If this worker hosted an actor that will restart, its creation task
        # must not seal error objects (the restarted creation reuses them).
        will_restart_actor = False
        creation_tid = None
        if worker.actor_id is not None:
            actor = self.actors.get(worker.actor_id)
            if actor is not None and actor.state != "DEAD":
                creation_tid = TaskID(actor.spec["creation_task"]["task_id"])
                will_restart_actor = actor.restarts_left != 0

        requeued_actor_tasks: List[TaskRecord] = []
        for tid in list(worker.inflight):
            task = self.tasks.get(tid)
            if task is None or task.state != RUNNING:
                continue
            if tid == creation_tid and will_restart_actor:
                # The restart path below resubmits this spec; the resubmitted
                # copy re-acquires at dispatch, so the running copy's
                # resources must be released here or the node leaks them.
                if not task.blocked:
                    self.scheduler.release(
                        task.node_id, task.resources, task.strategy
                    )
                continue
            # Actor tasks don't hold scheduler resources (the actor does);
            # a blocked task already released its resources in h_task_blocked.
            if (not task.spec.get("actor_id") or task.spec.get("is_actor_creation")) \
                    and not task.blocked:
                self.scheduler.release(task.node_id, task.resources, task.strategy)
            task.blocked = False
            if task.is_actor_task and will_restart_actor and task.retries_left != 0:
                # In-flight actor tasks survive the restart: requeue them at
                # the front so the restarted actor re-executes them in order
                # (reference: task_manager.cc resubmits actor tasks honoring
                # max_task_retries after actor restart).
                task.retries_left -= 1
                task.state = PENDING
                self._task_transition(task, "RETRYING",
                                      error="worker process died")
                task.worker_id = None
                task.node_id = None
                self._event("task_retry", task=task.task_id.hex())
                requeued_actor_tasks.append(task)
            elif task.retries_left != 0 and not task.spec.get("actor_id"):
                task.retries_left -= 1
                task.state = PENDING
                self._task_transition(task, "RETRYING",
                                      error="worker process died")
                task.worker_id = None
                self._event("task_retry", task=task.task_id.hex())
                self._enqueue_task(task)
            else:
                task.state = FAILED
                cause = (
                    " (killed by the memory monitor: host memory usage "
                    "crossed memory_usage_threshold)"
                    if oom_killed else ""
                )
                crash_msg = (
                    f"worker {worker_id.hex()[:8]} died while running "
                    f"task{cause}"
                )
                task.error = crash_msg
                # The FAILED record outlives the dead worker (and its node):
                # it lives in the head's task history, not the worker.
                self._task_transition(task, FAILED, error=crash_msg,
                                      traceback_text=crash_msg)
                err = serialization.pack(WorkerCrashedError(crash_msg))
                for raw in task.spec.get("return_ids", []):
                    rec = self._obj(ObjectID(raw))
                    rec.error = err
                    rec.sealed = True
                    self._notify_object_ready(rec.object_id)
                if task.spec.get("num_returns") == "streaming":
                    self.stream_done.setdefault(task.task_id, 0)
                    for key, evs in list(self.stream_waiters.items()):
                        if key[0] == task.task_id.binary():
                            for ev in self.stream_waiters.pop(key):
                                ev.set()
                task.end_time = time.time()
                self._finalize_task(task)

        if worker.actor_id is not None:
            actor = self.actors.get(worker.actor_id)
            if actor is not None and actor.state != "DEAD":
                # Surviving in-flight tasks go back to the front of the
                # actor's queue in submission order.
                for task in sorted(requeued_actor_tasks, key=lambda t: -t.seq):
                    actor.pending_tasks.appendleft(task)
                # Release the actor's creation resources (unless the creation
                # task itself was still running — handled in the loop above).
                ct = self.tasks.get(TaskID(actor.spec["creation_task"]["task_id"]))
                if ct is not None and ct.node_id is not None and ct.state == FINISHED:
                    self.scheduler.release(ct.node_id, ct.resources, ct.strategy)
                if actor.restarts_left != 0:
                    actor.restarts_left -= 1
                    actor.state = "RESTARTING"
                    actor.worker_id = None
                    await self._publish(
                        f"actor:{actor.actor_id.hex()}", {"state": "RESTARTING"}
                    )
                    # Invalidate cached peer routes: the restarted actor
                    # lands on a NEW worker (stale-incarnation calls to the
                    # old address also self-detect, this is the fast path).
                    await self._publish_actor_event(actor, "RESTARTING")
                    # Re-submit the creation task
                    # (reference: gcs_actor_manager.cc RestartActor).  The
                    # orphaned running record shares the task id; drop its
                    # arg pins first or re-registration double-pins them.
                    old_ct = self.tasks.get(
                        TaskID(actor.spec["creation_task"]["task_id"])
                    )
                    if old_ct is not None:
                        self._unpin_task_args(old_ct)
                    ct2 = TaskRecord(dict(actor.spec["creation_task"]))
                    self._register_task(ct2)
                    if not ct2.pending_deps:
                        self._enqueue_task(ct2)
                else:
                    actor.state = "DEAD"
                    self._mark_dirty()  # drop from the snapshot
                    actor.death_cause = "worker process died"
                    if actor.name:
                        self.named_actors.pop(actor.name, None)
                    await self._publish(
                        f"actor:{actor.actor_id.hex()}", {"state": "DEAD"}
                    )
                    await self._publish_actor_event(actor, "DEAD")
                    await self._fail_actor_queue(actor, None)
                    self._free_actor_creation_args(actor)
        self._kick()

    def _free_actor_creation_args(self, actor: ActorRecord):
        """Drop the creation-task large-args pin at permanent actor death
        (the creation task itself finalized long ago with keep=True)."""
        args_ref = actor.spec["creation_task"].get("args_ref")
        if args_ref is not None:
            self._decref(ObjectID(args_ref))

    # -- placement groups ------------------------------------------------------

    async def h_create_placement_group(self, conn, body):
        pg_id = PlacementGroupID(body["pg_id"])
        self.pg_bodies[pg_id] = body
        if conn is not None and body.get("lifetime") != "detached":
            self.pg_owner_conn[pg_id] = conn.conn_id
        self._mark_dirty()
        strategy = body.get("strategy", "PACK")
        ok = self.scheduler.create_placement_group(
            pg_id, body["bundles"], strategy, body.get("name", "")
        )
        if ok:
            self._notify_pg_ready(pg_id)
            return {"created": True}
        # Not placeable right now — either resources are busy or the bundles
        # don't fit the current node set at all.  Both queue (reference:
        # gcs_placement_group_manager keeps infeasible PGs pending so they
        # are satisfied when nodes join later); `infeasible_now` lets the
        # client warn that ready() will block until the cluster grows.
        feasible = self.scheduler.check_feasible_ever(body["bundles"], strategy)
        self.pending_pgs[pg_id] = body
        return {"created": False, "queued": True, "infeasible_now": not feasible}

    def _notify_pg_ready(self, pg_id: PlacementGroupID):
        for ev in self.pg_waiters.pop(pg_id, []):
            ev.set()

    def _try_pending_pgs(self):
        for pg_id in list(self.pending_pgs):
            body = self.pending_pgs[pg_id]
            if self.scheduler.create_placement_group(
                pg_id, body["bundles"], body.get("strategy", "PACK"),
                body.get("name", ""),
            ):
                del self.pending_pgs[pg_id]
                self._notify_pg_ready(pg_id)
            else:
                break  # FIFO fairness: head-of-line blocks later PGs

    async def h_pg_ready(self, conn, body):
        pg_id = PlacementGroupID(body["pg_id"])
        timeout = body.get("timeout", 30.0)
        deadline = time.monotonic() + timeout
        while pg_id in self.pending_pgs:
            ev = asyncio.Event()
            waiters = self.pg_waiters.setdefault(pg_id, [])
            waiters.append(ev)
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"ready": False}
                try:
                    await asyncio.wait_for(ev.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    return {"ready": False}
            finally:
                # Drop our event on timeout so repeated ready() polls on a
                # long-pending PG don't accumulate waiters.
                cur = self.pg_waiters.get(pg_id)
                if cur is not None and ev in cur:
                    cur.remove(ev)
        pg = self.scheduler.placement_groups.get(pg_id)
        return {"ready": pg is not None and pg.created}

    async def h_remove_placement_group(self, conn, body):
        pg_id = PlacementGroupID(body["pg_id"])
        self.pg_bodies.pop(pg_id, None)
        self.pg_owner_conn.pop(pg_id, None)
        self._mark_dirty()
        self.pending_pgs.pop(pg_id, None)
        self._notify_pg_ready(pg_id)
        self.scheduler.remove_placement_group(pg_id)
        self._kick()
        return {}

    # -- pubsub (reference: src/ray/pubsub/publisher.h) ------------------------

    async def h_publish(self, conn, body):
        await self._publish(body["topic"], body["data"])
        return {}

    async def _publish(self, topic: str, data):
        for conn_id in list(self.subs.get(topic, ())):
            c = self.server.connections.get(conn_id)
            if c is None:
                self.subs[topic].discard(conn_id)
                continue
            try:
                await c.push("pubsub", {"topic": topic, "data": data})
            except Exception:
                pass

    async def h_subscribe(self, conn, body):
        self.subs.setdefault(body["topic"], set()).add(conn.conn_id)
        return {}

    # -- introspection ---------------------------------------------------------

    async def h_cluster_resources(self, conn, body):
        total: Dict[str, float] = {}
        for n in self.scheduler.nodes.values():
            for k, v in n.total.items():
                total[k] = total.get(k, 0.0) + v
        return {"resources": total}

    async def h_available_resources(self, conn, body):
        total: Dict[str, float] = {}
        for n in self.scheduler.nodes.values():
            for k, v in n.available.items():
                total[k] = total.get(k, 0.0) + v
        return {"resources": total}

    # -- debugging plane: log retrieval + stack dumps --------------------------

    async def _node_call(self, addr: str, method: str, body: dict,
                         timeout: float = 10.0):
        """One-shot async RPC to a node daemon's server (the head is a
        *server* to daemons — their Connection only supports pushes — so
        routed reads like get_log dial the node's object-plane endpoint)."""
        from .rpc import ERR, REQ, RESP, RpcError, RpcServer, _encode, _read_msg

        host, port = addr.rsplit(":", 1)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port),
                                    limit=RpcServer.STREAM_LIMIT),
            timeout=timeout,
        )
        try:
            writer.write(_encode([REQ, 1, method, body]))
            await writer.drain()
            while True:
                mtype, _seq, _m, rbody = await asyncio.wait_for(
                    _read_msg(reader), timeout=timeout
                )
                if mtype == RESP:
                    return rbody
                if mtype == ERR:
                    raise RpcError(rbody)
        finally:
            writer.close()

    async def h_get_log(self, conn, body):
        """Ranged log read routed head -> owning node -> file.  Works for
        live AND exited processes (the index retains dead entries): the
        crash post-mortem path of `ray_tpu logs` and the dashboard."""
        query = str(body["proc_id"])
        entry, resolve_error = self._resolve_log_entry(query)
        if entry is None:
            return {"found": False, "error": resolve_error}
        if not entry["log_path"]:
            return {"found": False, "alive": entry["alive"],
                    "error": f"process {query!r} registered no log file"}
        offset = body.get("offset", 0)
        max_bytes = body.get("max_bytes", 65536)
        from .node_main import read_log_range

        node_hex = entry["node_id"]
        local_hex = self.local_node_id.hex() if self.local_node_id else ""
        reply: Optional[dict] = None
        if node_hex != local_hex:
            # Route to the owning node's daemon; a dead/unreachable node
            # falls back to a direct read (single-host clusters share the
            # filesystem, so post-mortems still work after node death).
            nid = next((n for n in self.node_object_addrs
                        if n.hex() == node_hex), None)
            addr = self.node_object_addrs.get(nid) if nid else None
            if addr is not None:
                try:
                    reply = await self._node_call(
                        addr, "read_log",
                        {"path": entry["log_path"], "offset": offset,
                         "max_bytes": max_bytes},
                    )
                except Exception:
                    reply = None
        if reply is None:
            reply = await asyncio.get_running_loop().run_in_executor(
                None, read_log_range, entry["log_path"], offset, max_bytes
            )
        reply["alive"] = entry["alive"]
        reply["proc"] = {k: entry[k] for k in
                         ("proc_id", "kind", "node_id", "pid", "actor_id")}
        return reply

    def _resolve_live_worker(self, query: str):
        """Resolve a worker by id hex prefix (or by hosting-actor id
        prefix) for the introspection round trips (stack dump, profile).
        Prefix resolution requires UNIQUENESS: during an incident,
        picking an arbitrary first match would silently debug the wrong
        process.  Returns (worker, None) or (None, error_reply)."""
        matches = [w for wid, w in self.workers.items()
                   if wid.hex() == query or wid.hex().startswith(query)]
        if not matches:
            # Accept an actor id: resolve to its hosting worker.
            matches = [
                self.workers[actor.worker_id]
                for aid, actor in self.actors.items()
                if actor.worker_id in self.workers
                and (aid.hex() == query or aid.hex().startswith(query))
            ]
        if len(matches) > 1:
            return None, {"found": False,
                          "error": f"{query!r} is ambiguous: matches "
                                   f"{len(matches)} workers — use a longer "
                                   "prefix (see `list workers`)"}
        worker = matches[0] if matches else None
        if worker is None or not worker.conn.alive:
            return None, {"found": False,
                          "error": f"no live worker matches {query!r}"}
        return worker, None

    async def h_stack_dump(self, conn, body):
        """All-thread Python stacks from a live worker, on demand and
        without interrupting the running task (the worker collects them on
        its rpc thread) — the hung-gang diagnosis tool (`ray_tpu stack`)."""
        worker, err = self._resolve_live_worker(str(body["worker_id"]))
        if worker is None:
            return err
        self._stack_token += 1
        token = self._stack_token
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._stack_waiters[token] = fut
        try:
            await worker.conn.push("stack_dump", {"token": token})
            reply = await asyncio.wait_for(
                fut, timeout=float(body.get("timeout", 10.0))
            )
        except asyncio.TimeoutError:
            return {"found": True, "ok": False,
                    "worker_id": worker.worker_id.hex(),
                    "error": "worker did not reply in time (rpc thread "
                             "wedged? try SIGUSR1 for a faulthandler dump "
                             "to its log file)"}
        except Exception as e:
            return {"found": True, "ok": False,
                    "worker_id": worker.worker_id.hex(), "error": str(e)}
        finally:
            self._stack_waiters.pop(token, None)
        return {
            "found": True, "ok": True,
            "worker_id": worker.worker_id.hex(),
            "node_id": worker.node_id.hex(),
            "pid": reply.get("pid", worker.pid),
            "threads": reply.get("threads", 0),
            "dump": reply.get("dump", ""),
        }

    async def h_stack_dump_reply(self, conn, body):
        fut = self._stack_waiters.get(body.get("token"))
        if fut is not None and not fut.done():
            fut.set_result(body)
        return {}

    async def h_profile(self, conn, body):
        """On-demand device-trace capture on a live worker (`ray_tpu
        profile`): a stack_dump-shaped token round trip, except the
        worker sleeps through an N-second jax.profiler capture before
        replying with the TensorBoard trace dir — so the wait deadline
        scales with the requested capture length."""
        worker, err = self._resolve_live_worker(str(body["worker_id"]))
        if worker is None:
            return err
        seconds = float(body["seconds"])
        self._stack_token += 1
        token = self._stack_token
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._profile_waiters[token] = fut
        push = {"token": token, "seconds": seconds}
        if body.get("logdir"):
            push["logdir"] = str(body["logdir"])
        try:
            await worker.conn.push("profile", push)
            reply = await asyncio.wait_for(
                fut, timeout=float(body.get("timeout", seconds + 30.0))
            )
        except asyncio.TimeoutError:
            return {"found": True, "ok": False,
                    "worker_id": worker.worker_id.hex(),
                    "error": f"worker did not finish the {seconds:.0f}s "
                             "capture in time (profiler wedged? check the "
                             "worker log)"}
        except Exception as e:
            return {"found": True, "ok": False,
                    "worker_id": worker.worker_id.hex(), "error": str(e)}
        finally:
            self._profile_waiters.pop(token, None)
        out = {
            "found": True, "ok": "error" not in reply,
            "worker_id": worker.worker_id.hex(),
            "node_id": worker.node_id.hex(),
            "pid": reply.get("pid", worker.pid),
        }
        if reply.get("logdir"):
            out["logdir"] = reply["logdir"]
        if reply.get("error"):
            out["error"] = reply["error"]
        return out

    async def h_profile_reply(self, conn, body):
        fut = self._profile_waiters.get(body.get("token"))
        if fut is not None and not fut.done():
            fut.set_result(body)
        return {}

    async def h_list_state(self, conn, body):
        kind = body["kind"]
        if kind == "nodes":
            return {"items": [
                {"node_id": nid.hex(), **info}
                for nid, info in (
                    (n.node_id, {"resources": n.total, "available": n.available,
                                 "alive": n.alive, "draining": n.draining,
                                 "labels": n.labels,
                                 "pending_spawns":
                                     self._spawn_pending.get(n.node_id, 0),
                                 "stats": self.node_stats.get(n.node_id)})
                    for n in self.scheduler.nodes.values()
                )
            ]}
        if kind == "actors":
            return {"items": [
                {
                    "actor_id": a.actor_id.hex(),
                    "class_name": a.spec.get("class_name", ""),
                    "state": a.state,
                    "name": a.name,
                    "pid": (self.workers[a.worker_id].pid
                            if a.worker_id in self.workers else None),
                    "num_executed_tasks": a.num_executed,
                }
                for a in self.actors.values()
            ]}
        if kind == "tasks":
            live = [
                {
                    "task_id": t.task_id.hex(),
                    "name": t.spec.get("name", ""),
                    "state": t.state,
                    "dep_blocked": bool(t.pending_deps),
                    "start_time": t.start_time,
                    "end_time": t.end_time,
                    "error": t.error,
                }
                for t in self.tasks.values()
                if t.state in (PENDING, RUNNING)  # terminal ones are in the ring
            ]
            return {"items": live + list(self.finished_tasks)}
        if kind == "objects":
            return {"items": [
                {
                    "object_id": o.object_id.hex(),
                    "size": o.size,
                    "sealed": o.sealed,
                    "inline": o.inline is not None,
                    "ref_count": o.ref_count,
                }
                for o in self.objects.values()
            ]}
        if kind == "workers":
            return {"items": [
                {
                    "worker_id": w.worker_id.hex(),
                    "node_id": w.node_id.hex(),
                    "state": w.state,
                    "pid": w.pid,
                }
                for w in self.workers.values()
            ]}
        if kind == "placement_groups":
            items = list(
                self.scheduler.snapshot()["placement_groups"].values()
            )
            # Queued (not-yet-placeable) PGs are cluster DEMAND — the
            # autoscaler keys off them, so they must be visible here
            # (reference: gcs_placement_group_manager pending queue feeds
            # the autoscaler's resource demand report).
            for pg_id, body in self.pending_pgs.items():
                items.append({
                    "pg_id": pg_id.hex(),
                    "strategy": body.get("strategy", "PACK"),
                    "created": False,
                    "pending": True,
                    # Current-node-set feasibility: lets demand consumers
                    # (autoscaler) distinguish "needs more nodes" from
                    # "waiting for busy resources to free".
                    "infeasible_now": not self.scheduler.check_feasible_ever(
                        body.get("bundles", []),
                        body.get("strategy", "PACK")),
                    "bundles": [
                        {"resources": dict(r), "node": None}
                        for r in body.get("bundles", [])
                    ],
                })
            return {"items": items}
        if kind == "timeline":
            return {"items": list(self.task_events)}
        if kind == "traces":
            # Span plane query surface: with trace_id (hex prefix ok),
            # the trace's raw spans; without, per-trace summary rows —
            # what `ray_tpu trace` and the dashboard's traces tab read.
            spans = [e for e in self.task_events if e.get("kind") == "span"]
            tid = body.get("trace_id")
            if tid:
                matched: Dict[str, list] = {}
                for s in spans:
                    sid = str(s.get("trace_id", ""))
                    if sid.startswith(str(tid)):
                        matched.setdefault(sid, []).append(s)
                if not matched:
                    return {"items": []}
                # A short hex prefix can match several traces: NEVER merge
                # them into one bogus tree — serve the most recent match
                # and name the others so the caller can disambiguate.
                pick = max(
                    matched,
                    key=lambda t: max(
                        (s.get("start") or 0) for s in matched[t]),
                ) if len(matched) > 1 else next(iter(matched))
                reply: Dict[str, Any] = {"items": matched[pick]}
                if len(matched) > 1:
                    reply["ambiguous_matches"] = sorted(matched)
                return reply
            from ..util import trace_analysis

            limit = body.get("limit")
            return {"items": trace_analysis.summarize(
                spans, limit=int(limit) if limit else 100)}
        if kind == "logs":
            # Cluster-wide log index, exited processes included (their
            # entries are what crash post-mortems route through).
            return {"items": [dict(e) for e in self.log_index.values()]}
        if kind == "task_events":
            items = list(self.task_history.values())
            tid = body.get("task_id")
            if tid:
                items = [r for r in items if r["task_id"].startswith(tid)]
            if body.get("errors"):
                items = [r for r in items if r["state"] == FAILED]
            return {"items": items}
        if kind == "metrics":
            return {"items": self.metrics_rows()}
        if kind == "metrics_history":
            return {"items": self.metrics_history.snapshot(
                body.get("name_prefix", ""))}
        if kind == "engine_steps":
            # Flight-recorder view: one row per engine with its latest
            # step record plus the retained window (optionally trimmed by
            # ``limit`` and filtered by an ``engine`` id prefix).
            engine = body.get("engine")
            limit = int(body.get("limit") or 0)
            items = []
            for eid, ring in self.engine_steps.items():
                if engine and not eid.startswith(str(engine)):
                    continue
                recs = list(ring)
                if limit > 0:
                    recs = recs[-limit:]
                items.append({
                    "engine": eid,
                    "latest": recs[-1] if recs else None,
                    "records": recs,
                })
            return {"items": items}
        if kind == "gang_rounds":
            # Gang observability view: one row per gang with its latest
            # joined skew profile plus the retained profile window
            # (optionally trimmed by ``limit`` and filtered by a ``gang``
            # id prefix) and the newest raw record per rank.
            gang = body.get("gang")
            limit = int(body.get("limit") or 0)
            items = []
            for gid, st in self.gang_rounds.items():
                if gang and not gid.startswith(str(gang)):
                    continue
                profs = list(st["profiles"])
                if limit > 0:
                    profs = profs[-limit:]
                items.append({
                    "gang": gid,
                    "world": st["world"],
                    "last_t": st["last_t"],
                    "latest": profs[-1] if profs else None,
                    "profiles": profs,
                    "ranks": {str(r): rec for r, rec in
                              sorted(st["latest_by_rank"].items())},
                })
            return {"items": items}
        if kind == "devmem":
            return {"items": sorted(
                self.devmem_by_pid.values(), key=lambda r: r["pid"])}
        if kind == "incidents":
            # Health plane: newest-first incident ring + the cluster grade
            # (`status`/`top` print the grade line from this same reply).
            mgr = self.health.manager
            items = mgr.snapshot()
            iid = body.get("id")
            if iid:
                items = [i for i in items if i["id"].startswith(str(iid))]
            return {"items": items, "grade": mgr.grade(),
                    "open": mgr.open_count()}
        raise ValueError(f"unknown state kind {kind!r}")

    async def h_shutdown_cluster(self, conn, body):
        asyncio.get_running_loop().call_soon(
            lambda: asyncio.ensure_future(self.stop())
        )
        return {}


def _validated(name: str, handler):
    """Boundary validation: malformed control-plane messages answer with a
    field-level error instead of a KeyError mid-handler (the protobuf-
    schema role — see core/schema.py)."""
    from . import schema as wire_schema
    from .rpc import RpcError

    async def wrapped(conn, body):
        try:
            wire_schema.validate(name, body)
        except wire_schema.SchemaError as e:
            raise RpcError(str(e)) from None
        return await handler(conn, body)

    wrapped.__name__ = f"validated_{name}"
    return wrapped


def env_jax_platform() -> str:
    # Inherit an explicit JAX_PLATFORMS (tests set cpu); default workers to cpu.
    return os.environ.get("JAX_PLATFORMS", "cpu")
