"""Per-request trace analysis: span-tree reconstruction, critical path,
stage breakdown, and an ASCII waterfall.

The span plane (``util/tracing.py``) lands finished spans in the head's
timeline ring; ``list_state(kind="traces")`` serves them back grouped by
trace id.  This module turns a trace's flat span list into the answers an
operator actually asks (reference: Ray's dashboard timeline + the
per-request latency breakdowns production serving systems expose):

- **tree**: parent/child reconstruction from (span_id, parent_id);
- **critical path**: the chain of spans that bounds the trace's wall
  time, with per-span self time (shrinking anything off this path cannot
  speed the request up);
- **stage breakdown**: wall time attributed to pipeline stages by span
  naming convention (ingress/handle/submit/schedule/queue/prefill/decode/
  execute/…), where *schedule* is the flow-arrow gap between a submit
  span and its execution span;
- **waterfall**: a terminal-width Gantt rendering of the tree.

Everything here is pure functions over span dicts — no cluster access —
so the CLI, the head, tests, and the bench harness share one
implementation.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: Span-name prefix -> stage label, first match wins (longest prefixes
#: first so ``engine:queue`` beats a hypothetical ``engine:`` rule).
STAGE_RULES: Tuple[Tuple[str, str], ...] = (
    ("ingress:", "ingress"),
    ("handle:", "handle"),
    ("submit:", "submit"),
    ("reroute:", "reroute"),
    ("replica:", "replica"),
    ("engine:queue", "queue"),
    ("engine:prefill", "prefill"),
    ("engine:decode", "decode"),
    ("task:", "execute"),
)


def _dur(span: Dict[str, Any]) -> float:
    try:
        return max(float(span["end"]) - float(span["start"]), 0.0)
    except (KeyError, TypeError, ValueError):
        return 0.0


def _valid(span: Dict[str, Any]) -> bool:
    return isinstance(span.get("start"), (int, float)) \
        and isinstance(span.get("end"), (int, float))


def stage_of(name: str) -> str:
    for prefix, stage in STAGE_RULES:
        if name.startswith(prefix):
            return stage
    return "other"


def summarize(events, limit: int = 100) -> List[Dict[str, Any]]:
    """Group timeline span events by trace id -> summary rows (most recent
    first).  ``events`` may be the raw timeline (non-span events are
    skipped)."""
    traces: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("kind") != "span" or not _valid(ev):
            continue
        tid = ev.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(ev)
    rows = []
    for tid, spans in traces.items():
        ids = {s.get("span_id") for s in spans}
        roots = [s for s in spans if s.get("parent_id") not in ids]
        root = min(roots or spans, key=lambda s: s["start"])
        start = min(s["start"] for s in spans)
        end = max(s["end"] for s in spans)
        rows.append({
            "trace_id": tid,
            "root": root.get("name", ""),
            "spans": len(spans),
            "start": round(start, 6),
            "duration_s": round(end - start, 6),
        })
    rows.sort(key=lambda r: -r["start"])
    return rows[:limit]


def build_tree(spans: List[dict]):
    """(roots, children) where children maps span_id -> child spans sorted
    by start.  A span whose parent_id is unknown (dropped, truncated ring)
    becomes a root — partial traces still render."""
    spans = [s for s in spans if _valid(s)]
    ids = {s.get("span_id") for s in spans}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent_id")
        if parent in ids and parent != s.get("span_id"):
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s["start"])
    roots.sort(key=lambda s: s["start"])
    return roots, children


def _merged_coverage(span: Dict[str, Any],
                     others: List[dict]) -> float:
    """Seconds of ``span``'s own interval covered by the union of the
    other spans' intervals (merged, so overlapping children don't double
    count)."""
    lo, hi = float(span["start"]), float(span["end"])
    clipped = sorted(
        (max(float(o["start"]), lo), min(float(o["end"]), hi))
        for o in others
    )
    covered = 0.0
    cur_lo: Optional[float] = None
    cur_hi = 0.0
    for s, e in clipped:
        if e <= s:
            continue
        if cur_lo is None:
            cur_lo, cur_hi = s, e
        elif s <= cur_hi:
            cur_hi = max(cur_hi, e)
        else:
            covered += cur_hi - cur_lo
            cur_lo, cur_hi = s, e
    if cur_lo is not None:
        covered += cur_hi - cur_lo
    return covered


def _descendants(span: Dict[str, Any], children) -> List[dict]:
    out: List[dict] = []
    stack = list(children.get(span.get("span_id") or "", []))
    while stack:
        s = stack.pop()
        out.append(s)
        stack.extend(children.get(s.get("span_id") or "", []))
    return out


def _walk_critical(node: Dict[str, Any], children, out: List[dict],
                   seen) -> None:
    """Backward sibling walk (the Jaeger-style critical path over a span
    tree): the last-finishing child bounds the tail of the parent; before
    that child starts, the latest-ending earlier sibling bounds the next
    segment; and so on — so a decode span's critical path includes the
    prefill that gated it, not just the deepest chain."""
    if id(node) in seen:
        return  # malformed parent links must not recurse forever
    seen.add(id(node))
    out.append(node)
    kids = sorted(children.get(node.get("span_id") or "", []),
                  key=lambda s: s["end"], reverse=True)
    cursor: Optional[float] = None
    for k in kids:
        if cursor is None or k["end"] <= cursor:
            _walk_critical(k, children, out, seen)
            cursor = float(k["start"])


def critical_path(spans: List[dict]) -> List[Dict[str, Any]]:
    """The span chain bounding the trace's wall time, chronological order.
    Each row carries the span's duration and its *self* time — the part
    of its interval not covered by its own descendants on the path
    (children may outlive their parents: a handle span closes at
    submission while the execution span runs on, so coverage is interval
    math, not child-duration subtraction).  Shrinking anything off this
    path cannot speed the request up."""
    roots, children = build_tree(spans)
    if not roots:
        return []
    path: List[dict] = []
    _walk_critical(max(roots, key=_dur), children, path, set())
    path.sort(key=lambda s: (s["start"], s["end"]))
    path_ids = {id(s) for s in path}
    out = []
    for s in path:
        on_path_desc = [d for d in _descendants(s, children)
                        if id(d) in path_ids]
        out.append({
            "name": s.get("name", ""),
            "span_id": s.get("span_id"),
            "stage": stage_of(str(s.get("name", ""))),
            "duration_s": _dur(s),
            "self_s": max(
                _dur(s) - _merged_coverage(s, on_path_desc), 0.0),
        })
    return out


def stage_breakdown(spans: List[dict]) -> Dict[str, float]:
    """Wall seconds per pipeline stage.  Each span contributes its SELF
    time — its interval minus the merged coverage of ALL its descendants
    (not just direct children: a handle span's execution-span child
    outlives it, so the grandparent ingress span must discount the
    grandchild too) — so nested stages never double count.  The
    submit→execute flow gap (attrs.flow_id, see tracing.chrome_trace) is
    attributed to ``schedule``."""
    spans = [s for s in spans if _valid(s)]
    _, children = build_tree(spans)
    out: Dict[str, float] = {}
    by_id = {s.get("span_id"): s for s in spans}
    for s in spans:
        desc = _descendants(s, children)
        self_s = max(_dur(s) - _merged_coverage(s, desc), 0.0)
        stage = stage_of(str(s.get("name", "")))
        out[stage] = out.get(stage, 0.0) + self_s
    # Scheduling gaps: submit span end -> execution span start.  The gap
    # wall time currently sits in the self time of the span the wait
    # happened INSIDE (the submit span's parent) — move it, don't double
    # count it, or stage shares would sum past 100%.
    for s in spans:
        flow = (s.get("attrs") or {}).get("flow_id")
        if not flow:
            continue
        exec_span = by_id.get(flow)
        if exec_span is None:
            continue
        gap = exec_span["start"] - s["end"]
        if gap <= 0:
            continue
        out["schedule"] = out.get("schedule", 0.0) + gap
        parent = by_id.get(s.get("parent_id"))
        if parent is not None:
            pstage = stage_of(str(parent.get("name", "")))
            out[pstage] = max(out.get(pstage, 0.0) - gap, 0.0)
    return out


def _fmt_ms(seconds: float) -> str:
    ms = seconds * 1e3
    if ms >= 100:
        return f"{ms:.0f}ms"
    if ms >= 1:
        return f"{ms:.1f}ms"
    return f"{ms:.3f}ms"


def render_waterfall(spans: List[dict], width: int = 64) -> str:
    """ASCII Gantt of the span tree: one line per span, bar positioned on
    the trace's wall-clock extent."""
    spans = [s for s in spans if _valid(s)]
    if not spans:
        return "(no spans)"
    roots, children = build_tree(spans)
    t0 = min(s["start"] for s in spans)
    total = max(max(s["end"] for s in spans) - t0, 1e-9)
    label_w = min(
        max(len(str(s.get("name", ""))) + 2 * _depth_cap for s in spans),
        40,
    )
    lines = []

    def walk(span, depth):
        name = str(span.get("name", ""))
        label = ("  " * min(depth, _depth_cap) + name)[:label_w]
        lo = int((span["start"] - t0) / total * width)
        hi = int((span["end"] - t0) / total * width)
        hi = max(hi, lo + 1)
        bar = " " * lo + "█" * (hi - lo) + " " * (width - hi)
        lines.append(
            f"{label:<{label_w}} |{bar}| {_fmt_ms(_dur(span)):>9}")
        for child in children.get(span.get("span_id") or "", []):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    header = f"{'span':<{label_w}} |{'total ' + _fmt_ms(total):<{width}}|"
    return "\n".join([header] + lines)


_depth_cap = 8


def format_trace(spans: List[dict]) -> str:
    """Full CLI rendering: waterfall + critical path + stage breakdown
    (what ``python -m ray_tpu trace <id>`` prints)."""
    spans = [s for s in spans if _valid(s)]
    if not spans:
        return "(no spans)"
    tid = spans[0].get("trace_id", "")
    t0 = min(s["start"] for s in spans)
    total = max(s["end"] for s in spans) - t0
    out = [f"trace {tid}  spans={len(spans)}  wall={_fmt_ms(total)}", ""]
    out.append(render_waterfall(spans))
    out.append("")
    out.append("critical path:")
    for row in critical_path(spans):
        out.append(
            f"  {row['name']:<40} {_fmt_ms(row['duration_s']):>9}"
            f"  (self {_fmt_ms(row['self_s'])})")
    out.append("")
    out.append("stage breakdown:")
    breakdown = stage_breakdown(spans)
    for stage, secs in sorted(breakdown.items(), key=lambda kv: -kv[1]):
        share = secs / total * 100 if total > 0 else 0.0
        out.append(f"  {stage:<10} {_fmt_ms(secs):>9}  {share:5.1f}%")
    return "\n".join(out)
