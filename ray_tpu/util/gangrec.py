"""Gang training flight recorder: a bounded per-rank round-record ring.

The training-plane twin of util/steprec.py (TorchTitan's per-rank step
recording posture, PAPERS.md): every ``train.report()`` appends ONE
fixed-size record per training round — step wall, data wait, collective
wait, lockstep-ack wait, checkpoint wall, compile time, tokens, MFU —
and this module gets it to three places without ever blocking the
training loop:

1. **Head join** — records drain as one batched ``gang_round_batch``
   RPC via the client's ``call_batched`` machinery on the background
   report cadence (exactly the span/steprec shape): they coalesce with
   task_done/span_batch traffic, hold bounded while headless, and
   replay at reconnect.  The head joins them by (gang, round) into skew
   profiles — which rank arrived last and which phase made it late.
   Ring overflow drops records — counted in
   ``ray_tpu_gang_rounds_dropped_total``, never silent.
2. **Black box** — the last ``gang_dump_records`` records are mirrored
   into a ``*.rounds.log`` sidecar next to the rank's own log file on
   every flush (throttled by ``gang_dump_interval_s``), so a SIGKILLed
   rank leaves its final rounds on disk for
   ``ray_tpu logs --post-mortem``.
3. **Tests/bench** — ``drain_buffered()`` hands back unflushed records
   for client-less harnesses (the train smoke bench's recorder-overhead
   gate drains this way).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.gangrec")

_ring: deque = deque()
_recent: deque = deque()  # last-N mirror for the black box (never drained)
_ring_lock = threading.Lock()
_dropped_total = 0
_warned_drop = False
_m_flushed = None
_m_dropped = None
_last_dump_t = 0.0
_dump_lock = threading.Lock()


def _cfg():
    from ..core.config import get_config

    return get_config()


def _ring_cap() -> int:
    try:
        return max(16, int(_cfg().gang_ring_size))
    except Exception:
        return 2048


def _dump_cap() -> int:
    try:
        return max(0, int(_cfg().gang_dump_records))
    except Exception:
        return 256


def _count_metric(which: str, n: int) -> None:
    """Lazily-resolved counters (the metrics registry lock must not sit
    on the training loop's record path)."""
    global _m_flushed, _m_dropped
    try:
        from .metrics import get_counter

        if which == "flushed":
            if _m_flushed is None:
                _m_flushed = get_counter(
                    "ray_tpu_gang_rounds_flushed_total",
                    "Gang round records shipped to the head "
                    "(batched flush)")
            _m_flushed.inc(n)
        else:
            if _m_dropped is None:
                _m_dropped = get_counter(
                    "ray_tpu_gang_rounds_dropped_total",
                    "Gang round records dropped (ring overflow or flush "
                    "failure) — counted, never silent")
            _m_dropped.inc(n)
    except Exception:
        pass  # metrics must never fail the recorder


def _note_dropped(n: int, why: str) -> None:
    global _dropped_total, _warned_drop
    _dropped_total += n
    _count_metric("dropped", n)
    if not _warned_drop:
        _warned_drop = True
        logger.warning(
            "dropping gang round records (%s; %d so far, counted in "
            "ray_tpu_gang_rounds_dropped_total) — raise gang_ring_size "
            "if this persists", why, _dropped_total)


def record_round(rec: Dict[str, Any]) -> None:
    """Append one round record: buffered into the bounded process-local
    ring for the next batched flush, and mirrored into the last-N black
    box.  Overflow drops the record (counted), never blocks the caller —
    this sits on the training loop's report() path."""
    dump_cap = _dump_cap()
    with _ring_lock:
        if dump_cap:
            if _recent.maxlen != dump_cap:
                # Config changed (or first record): rebuild the mirror.
                tail = list(_recent)[-dump_cap:]
                _recent.clear()
                _recent.__init__(tail, maxlen=dump_cap)
            _recent.append(rec)
        if len(_ring) < _ring_cap():
            _ring.append(rec)
            return
    _note_dropped(1, "gang round ring full")


def flush_rounds(client=None, sync: bool = False) -> int:
    """Drain the ring into ONE ``gang_round_batch`` head RPC via the
    client's ``call_batched`` (coalescing with task_done / span_batch),
    and refresh the black-box sidecar.  While headless this is a NO-OP
    for the RPC half — records stay in the BOUNDED ring and the first
    post-reconnect flush replays them — but the sidecar still refreshes.
    ``sync=True`` sends a blocking RPC instead (the run-end flush: the
    driver tears the gang down the moment the loops return, so the tail
    records must be IN the head, not in a fire-and-forget buffer, when
    this returns).  Returns the number of records flushed to the head."""
    dump_black_box()
    if client is None:
        from ..core.context import ctx as rt_ctx

        client = rt_ctx.client
    if client is None or getattr(client, "rpc", None) is None \
            or getattr(client.rpc, "closed", False):
        return 0
    with _ring_lock:
        if not _ring:
            return 0
        batch = list(_ring)
        _ring.clear()
    try:
        if sync:
            client.call("gang_round_batch", {"rounds": batch})
        else:
            client.call_batched("gang_round_batch", {"rounds": batch})
    except Exception:
        _note_dropped(len(batch), "gang_round_batch flush failed")
        return 0
    _count_metric("flushed", len(batch))
    return len(batch)


def drain_buffered() -> List[Dict[str, Any]]:
    """Remove and return every buffered (not-yet-flushed) record — for
    tests and client-less harnesses (the train smoke bench asserts
    round-record completeness this way)."""
    with _ring_lock:
        out = list(_ring)
        _ring.clear()
    return out


def dropped_total() -> int:
    return _dropped_total


# ------------------------------------------------------- head-side join

#: Phase keys a skew profile attributes lateness to.
PHASES = ("data", "compute", "checkpoint", "compile")


def _f(rec: Dict[str, Any], key: str) -> float:
    v = rec.get(key)
    return float(v) if isinstance(v, (int, float)) else 0.0


def _median(vals) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def skew_profile(rank_recs: Dict[int, Dict[str, Any]]
                 ) -> Optional[Dict[str, Any]]:
    """Join one (gang, round)'s per-rank records into a skew profile.

    A rank's *own time* is ``wall + checkpoint − collective wait`` — the
    part of the round it spent working rather than waiting on the gang
    (a straggler's lateness shows up as everyone ELSE's collective/ack
    wait, never its own).  The rank with the largest own time therefore
    arrived last at the round's sync points: it is the straggler, and the
    round's skew is its lead over the median own time.  The guilty phase
    is the straggler's largest positive deviation from the cross-rank
    median among data / compute / checkpoint / compile.

    Pure function over plain dicts (unit-testable without a head); the
    head calls it the moment a round has a record from every rank."""
    recs = {int(r): rec for r, rec in rank_recs.items()
            if isinstance(rec, dict)}
    if not recs:
        return None
    own: Dict[int, float] = {}
    phases: Dict[int, Dict[str, float]] = {}
    for r, rec in recs.items():
        wall = _f(rec, "wall_s")
        data = _f(rec, "data_s")
        coll = _f(rec, "coll_s")
        ckpt = _f(rec, "ckpt_s")
        comp = _f(rec, "compile_s")
        own[r] = wall + ckpt - coll
        phases[r] = {
            "data": data,
            "compute": max(0.0, wall - data - coll - comp),
            "checkpoint": ckpt,
            "compile": comp,
        }
    straggler = max(sorted(own), key=lambda r: own[r])
    # Skew is measured against the OTHER ranks' median own time — with the
    # straggler included, an even-sized gang would fold its own outlier
    # into the baseline (world=2 would always read zero skew).
    others = [own[r] for r in own if r != straggler]
    skew = max(0.0, own[straggler] - _median(others)) if others else 0.0
    dev = {ph: phases[straggler][ph]
           - _median(phases[r][ph] for r in phases) for ph in PHASES}
    phase = max(PHASES, key=lambda ph: dev[ph])
    med_wall = _median(_f(rec, "wall_s") for rec in recs.values())
    n = len(recs)
    mfus = [rec["mfu"] for rec in recs.values()
            if isinstance(rec.get("mfu"), (int, float))]
    tokens = [rec["tokens"] for rec in recs.values()
              if isinstance(rec.get("tokens"), (int, float))]
    any_rec = next(iter(recs.values()))
    return {
        "gang": str(any_rec.get("gang", "?")),
        "round": any_rec.get("round"),
        "world": n,
        "t": max(_f(rec, "t") for rec in recs.values()),
        "wall_s": round(med_wall, 6),
        "skew_s": round(skew, 6),
        "skew_frac": round(skew / med_wall, 4) if med_wall > 0 else 0.0,
        "straggler": straggler,
        "phase": phase,
        "phase_lag_s": round(max(0.0, dev[phase]), 6),
        "data_frac": round(
            sum(phases[r]["data"] for r in phases) / n / med_wall, 4)
        if med_wall > 0 else 0.0,
        "coll_frac": round(
            sum(_f(rec, "coll_s") for rec in recs.values()) / n / med_wall,
            4) if med_wall > 0 else 0.0,
        "ack_s": round(
            sum(_f(rec, "ack_s") for rec in recs.values()) / n, 6),
        "ckpt_s": round(
            sum(_f(rec, "ckpt_s") for rec in recs.values()) / n, 6),
        "mfu": round(sum(mfus) / len(mfus), 4) if mfus else None,
        "tokens": int(sum(tokens)) if tokens else None,
    }


# ------------------------------------------------------------- black box


def black_box_path() -> Optional[str]:
    """Sidecar path next to this process's managed log file (None when
    the process has no spawner-assigned log, e.g. a driver).  Named
    ``<log>.rounds.log`` so the post-mortem glob over ``LOG_ROOT/*/*.log``
    picks it up alongside the log tails."""
    log_path = os.environ.get("RT_LOG_PATH")
    if not log_path:
        return None
    stem = log_path[:-4] if log_path.endswith(".log") else log_path
    return stem + ".rounds.log"


def dump_black_box(path: Optional[str] = None, force: bool = False) -> bool:
    """Rewrite the sidecar with the last-N records as compact JSON lines.
    Throttled by ``gang_dump_interval_s`` unless ``force``.  Returns True
    when a file was written.  Never raises — a full disk must not take
    down the training loop."""
    global _last_dump_t
    if path is None:
        path = black_box_path()
    if path is None or not _dump_cap():
        return False
    now = time.monotonic()
    with _dump_lock:
        if not force and now - _last_dump_t < \
                max(0.0, float(getattr(_cfg(), "gang_dump_interval_s", 1.0))):
            return False
        with _ring_lock:
            records = list(_recent)
        if not records:
            return False
        _last_dump_t = now
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"# ray_tpu gang round flight recorder black box "
                        f"(pid={os.getpid()}, last {len(records)} rounds)\n")
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
            os.replace(tmp, path)  # atomic: a crash mid-dump keeps the old box
            return True
        except OSError:
            return False
