"""Distributed tracing: span context propagation across task boundaries.

Role-equivalent to the reference's OpenTelemetry integration
(reference: python/ray/util/tracing/tracing_helper.py — _DictPropagator:165
injects the active span context into task specs; spans wrap submission and
execution) — re-designed without an OTel dependency: trace context is a
(trace_id, span_id) pair carried in the task spec, spans are recorded into
the head's timeline ring (task_event_buffer.h's role) and exported as a
Chrome trace by ``python -m ray_tpu timeline --chrome`` (or per-trace via
``python -m ray_tpu trace <id> --chrome``).

Emission is a **batched span plane**: finished spans buffer in a bounded
per-process ring (``span_ring_size``) and flush as ONE ``span_batch`` head
RPC on the background-report cadence — never one RPC per span.  The flush
rides the client's ``call_batched`` machinery, so spans coalesce with
task_done reports and, while the head connection is down, buffer and
replay at reconnect exactly like completion reports (head-restart safe).
Ring overflow and flush failures are counted in
``ray_tpu_spans_dropped_total`` and logged once per process — drops are
visible, never silent.

Root spans roll a head-configured sample rate (``trace_sample_rate``,
handed to every process in the register reply); ``trace(..., force=True)``
is the per-call override.  Inside an unsampled root, nested spans and
task submissions stay span-free end to end (zero propagation overhead).

Usage::

    with tracing.trace("preprocess"):       # user span inside a task
        ...
    # Submission inside a traced region propagates (trace_id, span_id) to
    # the child task automatically; the child's execution span is recorded
    # with parent_id linking the tree.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..core.ids import _rand_bytes

_current: contextvars.ContextVar[Optional[Dict[str, Any]]] = (
    contextvars.ContextVar("rt_trace_ctx", default=None)
)

#: Sentinel context installed for an UNSAMPLED trace root: nested
#: ``trace()`` calls and task submissions inside it emit nothing and
#: propagate nothing, but the nesting discipline still holds.
_UNSAMPLED: Dict[str, Any] = {"sampled": False}

logger = logging.getLogger("ray_tpu.tracing")


def new_id() -> str:
    """A fresh 64-bit hex span/trace id (public — use this instead of the
    legacy private ``_new_id``).  Backed by the fork-keyed process PRNG
    from ``core/ids`` — ``os.urandom`` is a syscall per call (~1 ms on
    sandboxed kernels) and span ids are minted on the submission hot
    path; the PRNG stream resets in forked children, so uniqueness holds
    across zygote forks."""
    return _rand_bytes(8).hex()


_new_id = new_id  # backward-compat alias


# ------------------------------------------------------------- sampling


def _sample_rate() -> float:
    """Head-configured root sampling rate: the register reply carries the
    head's ``trace_sample_rate`` (one knob governs the cluster); processes
    without a client fall back to their local config."""
    from ..core.context import ctx as rt_ctx

    client = rt_ctx.client
    rate = getattr(client, "trace_sample_rate", None) \
        if client is not None else None
    if rate is None:
        try:
            from ..core.config import get_config

            rate = get_config().trace_sample_rate
        except Exception:
            rate = 1.0
    return float(rate)


def should_sample(force: bool = False) -> bool:
    """Root-trace sampling decision.  ``force=True`` is the per-call
    override (always traces); otherwise roll against the head-configured
    rate."""
    if force:
        return True
    rate = _sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int.from_bytes(_rand_bytes(4), "little") < rate * 2.0**32


# ------------------------------------------------------------- span ring

_ring: deque = deque()
_ring_lock = threading.Lock()
_dropped_total = 0
_warned_drop = False
_m_emitted = None
_m_dropped = None


def _ring_cap() -> int:
    try:
        from ..core.config import get_config

        return max(16, int(get_config().span_ring_size))
    except Exception:
        return 4096


def _count_metric(which: str, n: int) -> None:
    """Lazily-resolved counters (the metrics registry lock must not sit on
    the emit path)."""
    global _m_emitted, _m_dropped
    try:
        from .metrics import get_counter

        if which == "emitted":
            if _m_emitted is None:
                _m_emitted = get_counter(
                    "ray_tpu_spans_emitted_total",
                    "Tracing spans shipped to the head (batched flush)")
            _m_emitted.inc(n)
        else:
            if _m_dropped is None:
                _m_dropped = get_counter(
                    "ray_tpu_spans_dropped_total",
                    "Tracing spans dropped (ring overflow or flush "
                    "failure) — counted, never silent")
            _m_dropped.inc(n)
    except Exception:
        pass  # metrics must never fail the span plane


def _note_dropped(n: int, why: str) -> None:
    global _dropped_total, _warned_drop
    _dropped_total += n
    _count_metric("dropped", n)
    if not _warned_drop:
        _warned_drop = True
        logger.warning(
            "dropping tracing spans (%s; %d so far, counted in "
            "ray_tpu_spans_dropped_total) — raise span_ring_size or lower "
            "trace_sample_rate if this persists", why, _dropped_total)


def emit_span(span: Dict[str, Any]) -> None:
    """Record a finished span: buffered into the process-local ring and
    shipped in the next batched flush (NO per-span head RPC).  Public —
    use this instead of the legacy private ``_emit``.  The span dict needs
    at least trace_id/span_id/name; start/end are float timestamps in
    seconds.  Ring overflow drops the span (counted), never blocks."""
    with _ring_lock:
        if len(_ring) < _ring_cap():
            _ring.append(span)
            return
    _note_dropped(1, "span ring full")


_emit = emit_span  # backward-compat alias


def flush_spans(client=None) -> int:
    """Drain the ring into ONE ``span_batch`` head RPC via the client's
    ``call_batched`` — so span traffic coalesces with task_done reports.
    While headless (lost head connection) this is a NO-OP: spans stay in
    the BOUNDED ring (overflow drops counted) instead of growing the
    client's held submit batch without limit for the whole outage, and
    the first post-reconnect flush replays them.  Called from the
    client's background flush loop (the existing report cadence), the
    worker's idle loop, and the shutdown drains.  Returns the number of
    spans flushed."""
    if client is None:
        from ..core.context import ctx as rt_ctx

        client = rt_ctx.client
    if client is None or getattr(client, "rpc", None) is None \
            or getattr(client.rpc, "closed", False):
        return 0
    with _ring_lock:
        if not _ring:
            return 0
        batch = list(_ring)
        _ring.clear()
    try:
        client.call_batched("span_batch", {"spans": batch})
    except Exception:
        _note_dropped(len(batch), "span_batch flush failed")
        return 0
    _count_metric("emitted", len(batch))
    return len(batch)


def drain_buffered() -> List[Dict[str, Any]]:
    """Remove and return every buffered (not-yet-flushed) span — for tests
    and client-less diagnostics (bench harnesses assert span-tree
    completeness this way)."""
    with _ring_lock:
        out = list(_ring)
        _ring.clear()
    return out


# ------------------------------------------------------------- context


def current_context() -> Optional[Dict[str, Any]]:
    """The active {trace_id, span_id}, or None outside any trace.  Inside
    an unsampled root this returns the unsampled sentinel."""
    return _current.get()


def context_for_submit() -> Optional[Dict[str, str]]:
    """Trace context to inject into an outgoing task spec (reference:
    _DictPropagator.inject_current_context).  None outside any trace AND
    inside an unsampled root — unsampled traces propagate nothing."""
    ctx = _current.get()
    if ctx is None or not ctx.get("sampled", True):
        return None
    return ctx


def _safe_reset(token, installed=None) -> None:
    """Reset the context var, tolerating a generator finalized on a
    different thread than the one that opened the span (pool-driven
    generators): the token then belongs to another thread's context.  In
    that case clear ONLY if the finalizing thread's active context is
    this very span — never wipe an unrelated concurrent request's
    context."""
    try:
        _current.reset(token)
    except ValueError:
        if installed is not None and _current.get() is installed:
            _current.set(None)


def set_context(ctx: Optional[Dict[str, str]]):
    """Install the context received with an executing task; returns a token
    for reset."""
    return _current.set(ctx)


def reset_context(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def trace(name: str, force: bool = False, **attrs):
    """A named span.  Nested spans and tasks submitted inside it become
    children; the finished span lands in the cluster timeline.  Root
    spans roll the head-configured ``trace_sample_rate``; ``force=True``
    always traces this root (the per-call override).  Extra keyword
    arguments become span attrs."""
    parent = _current.get()
    if parent is not None and not parent.get("sampled", True):
        # Inside an unsampled root: stay span-free, keep the sentinel.
        yield parent
        return
    if parent is None and not should_sample(force):
        # Fresh dict per root (not the shared sentinel): callers may
        # write into the yielded ctx's "attrs" (see below) and must not
        # poison other traces.
        unsampled = {"sampled": False}
        token = _current.set(unsampled)
        try:
            yield unsampled
        finally:
            _safe_reset(token, unsampled)
        return
    span_ctx = {
        "trace_id": parent["trace_id"] if parent else new_id(),
        "span_id": new_id(),
    }
    token = _current.set(span_ctx)
    start = time.time()
    try:
        yield span_ctx
    finally:
        _safe_reset(token, span_ctx)
        # Late attrs: values the caller only learns inside the span (the
        # handle's final replica pick after a retry) merge over the
        # entry-time kwargs via the yielded ctx's "attrs" key.
        late = span_ctx.get("attrs")
        if late:
            attrs = {**attrs, **late}
        emit_span({
            "trace_id": span_ctx["trace_id"],
            "span_id": span_ctx["span_id"],
            "parent_id": parent["span_id"] if parent else None,
            "name": name,
            "start": start,
            "end": time.time(),
            "pid": os.getpid(),
            **({"attrs": attrs} if attrs else {}),
        })


def make_span(parent_ctx: Dict[str, str], name: str, start: float,
              end: float, **attrs) -> Dict[str, Any]:
    """Build a finished-span dict against an explicit parent context —
    for emitters that can't use the ``trace()`` context manager (the
    engine's loop thread stamping another thread's request, the
    dataplane's reroute marker).  Pair with :func:`emit_span`."""
    return {
        "trace_id": parent_ctx["trace_id"],
        "span_id": new_id(),
        "parent_id": parent_ctx.get("span_id"),
        "name": name,
        "start": start,
        "end": end,
        "pid": os.getpid(),
        **({"attrs": attrs} if attrs else {}),
    }


def trace_if_active(name: str, **attrs):
    """``trace()`` only when a SAMPLED context is already active — the
    propagation-only span the serve handle/replica layers use: untraced
    or unsampled callers pay nothing and root nothing.  Yields a dict
    either way; writes to its ``"attrs"`` key merge into the emitted
    span (no-op when inactive)."""
    if context_for_submit() is None:
        return contextlib.nullcontext({})
    return trace(name, **attrs)


def task_span(spec: Dict[str, Any], start: float, end: float,
              **attrs) -> Optional[dict]:
    """Build the execution span for a finished task from its spec's injected
    context (None when the submission wasn't traced and tracing isn't
    forced)."""
    injected = spec.get("trace_ctx")
    if injected is None:
        return None
    return {
        "trace_id": injected["trace_id"],
        "span_id": injected.get("task_span_id") or new_id(),
        "parent_id": injected.get("span_id"),
        "name": f"task:{spec.get('name', 'anonymous')}",
        "start": start,
        "end": end,
        "pid": os.getpid(),
        **({"attrs": attrs} if attrs else {}),
    }


def chrome_trace(events) -> list:
    """Convert timeline span events into Chrome trace-event JSON (the
    `ray timeline` output format — reference: chrome://tracing 'X' complete
    events keyed by pid/tid).

    Submission spans carry ``attrs.flow_id`` (the pre-assigned execution
    span id, see api._inject_trace): each such pair additionally emits a
    flow-event arrow ('s' at the submit span's end, 'f' at the execution
    span's start) so the timeline renders the scheduling gap between
    submit and execute as a visible edge."""
    out = []
    spans = []
    for ev in events:
        if ev.get("kind") != "span":
            continue
        if not isinstance(ev.get("start"), (int, float)) \
                or not isinstance(ev.get("end"), (int, float)):
            continue  # malformed emitter: skip, don't kill the export
        spans.append(ev)
        out.append({
            "name": ev.get("name", "span"),
            "cat": ev.get("trace_id", ""),
            "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(ev["end"] - ev["start"], 0) * 1e6,
            "pid": ev.get("pid", 0),
            "tid": ev.get("pid", 0),
            "args": {
                "trace_id": ev.get("trace_id"),
                "span_id": ev.get("span_id"),
                "parent_id": ev.get("parent_id"),
                **(ev.get("attrs") or {}),
            },
        })
    # Flow arrows: submit span (attrs.flow_id) -> execution span (span_id).
    flow_starts = {}
    for ev in spans:
        flow = (ev.get("attrs") or {}).get("flow_id")
        if flow:
            flow_starts[flow] = ev
    if flow_starts:
        for ev in spans:
            sub = flow_starts.get(ev.get("span_id"))
            if sub is None or ev is sub:
                continue
            common = {"cat": "scheduling", "id": ev["span_id"],
                      "name": "submit_to_start"}
            out.append({**common, "ph": "s", "ts": sub["end"] * 1e6,
                        "pid": sub.get("pid", 0), "tid": sub.get("pid", 0)})
            out.append({**common, "ph": "f", "bp": "e",
                        "ts": ev["start"] * 1e6,
                        "pid": ev.get("pid", 0), "tid": ev.get("pid", 0)})
    return out
