"""Distributed tracing: span context propagation across task boundaries.

Role-equivalent to the reference's OpenTelemetry integration
(reference: python/ray/util/tracing/tracing_helper.py — _DictPropagator:165
injects the active span context into task specs; spans wrap submission and
execution) — re-designed without an OTel dependency: trace context is a
(trace_id, span_id) pair carried in the task spec, spans are recorded into
the head's timeline ring (task_event_buffer.h's role) and exported as a
Chrome trace by ``python -m ray_tpu timeline --chrome``.

Usage::

    with tracing.trace("preprocess"):       # user span inside a task
        ...
    # Submission inside a traced region propagates (trace_id, span_id) to
    # the child task automatically; the child's execution span is recorded
    # with parent_id linking the tree.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Any, Dict, Optional

_current: contextvars.ContextVar[Optional[Dict[str, str]]] = (
    contextvars.ContextVar("rt_trace_ctx", default=None)
)


def new_id() -> str:
    """A fresh 64-bit hex span/trace id (public — use this instead of the
    legacy private ``_new_id``)."""
    return os.urandom(8).hex()


_new_id = new_id  # backward-compat alias


def current_context() -> Optional[Dict[str, str]]:
    """The active {trace_id, span_id}, or None outside any trace."""
    return _current.get()


def context_for_submit() -> Optional[Dict[str, str]]:
    """Trace context to inject into an outgoing task spec (reference:
    _DictPropagator.inject_current_context)."""
    return _current.get()


def set_context(ctx: Optional[Dict[str, str]]):
    """Install the context received with an executing task; returns a token
    for reset."""
    return _current.set(ctx)


def reset_context(token) -> None:
    _current.reset(token)


def emit_span(span: Dict[str, Any]) -> None:
    """Record a finished span into the cluster timeline (best-effort).
    Public — use this instead of the legacy private ``_emit``.  The span
    dict needs at least trace_id/span_id/name; start/end are float
    timestamps in seconds."""
    from ..core.context import ctx as rt_ctx

    if rt_ctx.client is None:
        return
    try:
        rt_ctx.client.call_bg("span", span)
    except Exception:
        pass


_emit = emit_span  # backward-compat alias


@contextlib.contextmanager
def trace(name: str, **attrs):
    """A named span.  Nested spans and tasks submitted inside it become
    children; the finished span lands in the cluster timeline."""
    parent = _current.get()
    span_ctx = {
        "trace_id": parent["trace_id"] if parent else new_id(),
        "span_id": new_id(),
    }
    token = _current.set(span_ctx)
    start = time.time()
    try:
        yield span_ctx
    finally:
        _current.reset(token)
        emit_span({
            "trace_id": span_ctx["trace_id"],
            "span_id": span_ctx["span_id"],
            "parent_id": parent["span_id"] if parent else None,
            "name": name,
            "start": start,
            "end": time.time(),
            "pid": os.getpid(),
            **({"attrs": attrs} if attrs else {}),
        })


def task_span(spec: Dict[str, Any], start: float, end: float) -> Optional[dict]:
    """Build the execution span for a finished task from its spec's injected
    context (None when the submission wasn't traced and tracing isn't
    forced)."""
    injected = spec.get("trace_ctx")
    if injected is None:
        return None
    return {
        "trace_id": injected["trace_id"],
        "span_id": injected.get("task_span_id") or new_id(),
        "parent_id": injected.get("span_id"),
        "name": f"task:{spec.get('name', 'anonymous')}",
        "start": start,
        "end": end,
        "pid": os.getpid(),
    }


def chrome_trace(events) -> list:
    """Convert timeline span events into Chrome trace-event JSON (the
    `ray timeline` output format — reference: chrome://tracing 'X' complete
    events keyed by pid/tid).

    Submission spans carry ``attrs.flow_id`` (the pre-assigned execution
    span id, see api._inject_trace): each such pair additionally emits a
    flow-event arrow ('s' at the submit span's end, 'f' at the execution
    span's start) so the timeline renders the scheduling gap between
    submit and execute as a visible edge."""
    out = []
    spans = []
    for ev in events:
        if ev.get("kind") != "span":
            continue
        if not isinstance(ev.get("start"), (int, float)) \
                or not isinstance(ev.get("end"), (int, float)):
            continue  # malformed emitter: skip, don't kill the export
        spans.append(ev)
        out.append({
            "name": ev.get("name", "span"),
            "cat": ev.get("trace_id", ""),
            "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(ev["end"] - ev["start"], 0) * 1e6,
            "pid": ev.get("pid", 0),
            "tid": ev.get("pid", 0),
            "args": {
                "trace_id": ev.get("trace_id"),
                "span_id": ev.get("span_id"),
                "parent_id": ev.get("parent_id"),
                **(ev.get("attrs") or {}),
            },
        })
    # Flow arrows: submit span (attrs.flow_id) -> execution span (span_id).
    flow_starts = {}
    for ev in spans:
        flow = (ev.get("attrs") or {}).get("flow_id")
        if flow:
            flow_starts[flow] = ev
    if flow_starts:
        for ev in spans:
            sub = flow_starts.get(ev.get("span_id"))
            if sub is None or ev is sub:
                continue
            common = {"cat": "scheduling", "id": ev["span_id"],
                      "name": "submit_to_start"}
            out.append({**common, "ph": "s", "ts": sub["end"] * 1e6,
                        "pid": sub.get("pid", 0), "tid": sub.get("pid", 0)})
            out.append({**common, "ph": "f", "bp": "e",
                        "ts": ev["start"] * 1e6,
                        "pid": ev.get("pid", 0), "tid": ev.get("pid", 0)})
    return out
