"""Deterministic network fault injection for the RPC transport.

Role-equivalent to the reference's network chaos tooling (reference:
release/nightly_tests/chaos_test/ + the gcs_health_check_manager tests that
perturb connection health): a seeded :class:`FaultSchedule` that
``core/rpc.py`` consults on every client send, client receive, and server
accept.  Unlike ``util/chaos.py`` (clean process kills), this layer models
the faults a real network serves: lost requests, lost replies, duplicated
replies, added latency, partitions between named endpoints, and gray
failures (a peer that accepts connections but never answers).

Armed two ways:

- ``RT_NETFAULT`` + ``RT_NETFAULT_SEED`` in the environment — every process
  that opens an RPC endpoint arms the same schedule spec (children inherit
  the env, so a cluster-wide partition needs one export).
- :func:`arm` / :func:`disarm` in-process (tests).

Zero overhead when off: the transport's hot paths check one module global
against ``None`` and touch nothing else.

Schedule DSL — semicolon-separated rules, ``kind:key=val,key=val``::

    drop_request:link=peer-direct,p=0.3      # lose 30% of peer requests
    drop_reply:link=driver-rpc,method=ping   # lose every ping reply
    dup_reply:link=peer-direct,p=0.1         # deliver 10% of replies twice
    delay:link=node-rpc,ms=50,dist=exp       # ~exp(50ms) added latency
    stall:link=peer-server,dur=5             # accept, answer nothing for 5s
    partition:link=node-rpc,at=1,dur=5       # head<->node dark for 5s
    partition:link=peer-direct,mode=out      # one-way: requests vanish,
                                             # replies still arrive

Keys: ``link=`` substring-matches the connection/server name (clients:
``driver-rpc``/``worker-rpc``/``node-rpc`` for the head link,
``peer-direct`` for the peer plane; servers: ``head-server``,
``node-server``, ``peer-server``).  ``method=`` exact-matches the RPC
method.  ``p=`` is the injection probability (default 1).  ``at=``/``dur=``
bound the rule to an arm-relative time window (seconds).  ``ms=`` is the
delay in milliseconds (``dist=exp`` draws from an exponential with that
mean; default fixed).  ``mode=sym|in|out`` sets partition direction
(symmetric, inbound-only — replies dropped, or outbound-only — requests
dropped).

Replayability: every probabilistic decision comes from a counter-indexed
``random.Random`` derived from (seed, rule, decision#) with integer
arithmetic only — the same seed and traffic order reproduce the same fault
sequence, and a soak failure replays from its printed seed.
"""

from __future__ import annotations

import random
import sys
import time
from typing import Dict, List, Optional

from ..devtools.locks import guarded, make_lock

KINDS = ("drop_request", "drop_reply", "delay", "dup_reply", "stall",
         "partition")


class _Rule:
    __slots__ = ("kind", "link", "method", "p", "at", "dur", "ms", "dist",
                 "mode")

    def __init__(self, kind: str):
        self.kind = kind
        self.link: Optional[str] = None
        self.method: Optional[str] = None
        self.p = 1.0
        self.at = 0.0
        self.dur: Optional[float] = None
        self.ms = 0.0
        self.dist = "fixed"
        self.mode = "sym"

    def describe(self) -> str:
        keys = []
        if self.link:
            keys.append(f"link={self.link}")
        if self.method:
            keys.append(f"method={self.method}")
        if self.p < 1.0:
            keys.append(f"p={self.p}")
        if self.at:
            keys.append(f"at={self.at}")
        if self.dur is not None:
            keys.append(f"dur={self.dur}")
        if self.kind == "delay":
            keys.append(f"ms={self.ms}")
        if self.kind == "partition" and self.mode != "sym":
            keys.append(f"mode={self.mode}")
        return f"{self.kind}:{','.join(keys)}" if keys else self.kind


def _parse(spec: str) -> List[_Rule]:
    rules: List[_Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"netfault: unknown fault kind {kind!r} (one of {KINDS})")
        rule = _Rule(kind)
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, _, val = kv.partition("=")
            if key == "link":
                rule.link = val
            elif key == "method":
                rule.method = val
            elif key == "p":
                rule.p = float(val)
            elif key == "at":
                rule.at = float(val)
            elif key == "dur":
                rule.dur = float(val)
            elif key == "ms":
                rule.ms = float(val)
            elif key == "dist":
                rule.dist = val
            elif key == "mode":
                rule.mode = val
            else:
                raise ValueError(f"netfault: unknown rule key {key!r}")
        rules.append(rule)
    return rules


@guarded
class FaultSchedule:
    """A parsed, seeded schedule.  Decision entry points are called from
    RPC loop threads (one per connection/server) concurrently."""

    # rtlint RT007 verifies these statically; RT_DEBUG_LOCKS=2 asserts the
    # guards at runtime (devtools.locks).
    _RT_GUARDED_BY = {
        "counts": "_lock",
        "_decisions": "_lock",
    }

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self.rules = _parse(spec)
        self._t0 = time.monotonic()
        self._lock = make_lock("netfault.decisions")
        #: per-rule decision counters — the replay index
        self._decisions = [0] * len(self.rules)
        #: injections actually performed, by kind (assertion hook)
        self.counts: Dict[str, int] = {}
        self._counter = None

    # ------------------------------------------------------------ matching

    def _window_open(self, rule: _Rule, now: float) -> bool:
        t = now - self._t0
        if t < rule.at:
            return False
        return rule.dur is None or t < rule.at + rule.dur

    @staticmethod
    def _match(rule: _Rule, link: str, method: Optional[str]) -> bool:
        if rule.link is not None and rule.link not in link:
            return False
        if rule.method is not None and method != rule.method:
            return False
        return True

    def _decide(self, idx: int, rule: _Rule) -> Optional[random.Random]:
        """One deterministic coin flip for rule ``idx``.  Integer-seeded so
        the sequence is independent of PYTHONHASHSEED and replays exactly
        for a given (seed, traffic order)."""
        with self._lock:
            n = self._decisions[idx]
            self._decisions[idx] = n + 1
        rng = random.Random((self.seed * 1_000_003 + idx) * 1_000_003 + n)
        if rule.p >= 1.0 or rng.random() < rule.p:
            return rng
        return None

    def _record(self, kind: str):
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
        try:
            if self._counter is None:
                from .metrics import get_counter

                self._counter = get_counter(
                    "ray_tpu_netfaults_injected_total",
                    "Network faults injected by the netfault schedule",
                    tag_keys=("kind",),
                )
            self._counter.inc(1, tags={"kind": kind})
        except Exception:
            pass  # metrics must never fail an injection site

    # ----------------------------------------------------------- decisions

    def on_send(self, link: str, method: str) -> Optional[dict]:
        """Client about to write a request frame.  Returns None (deliver)
        or {"kind": "drop"} / {"kind": "delay", "delay_s": s}."""
        now = time.monotonic()
        for idx, rule in enumerate(self.rules):
            if not self._window_open(rule, now):
                continue
            if not self._match(rule, link, method):
                continue
            if rule.kind == "drop_request" or (
                    rule.kind == "partition" and rule.mode in ("sym", "out")):
                if self._decide(idx, rule) is not None:
                    self._record(rule.kind)
                    return {"kind": "drop"}
            elif rule.kind == "delay":
                rng = self._decide(idx, rule)
                if rng is not None:
                    s = rule.ms / 1000.0
                    if rule.dist == "exp":
                        s = rng.expovariate(1.0 / s) if s > 0 else 0.0
                    self._record("delay")
                    return {"kind": "delay", "delay_s": s}
        return None

    def on_recv(self, link: str, method: str) -> Optional[dict]:
        """Client received a reply/push frame.  Returns None (deliver) or
        {"kind": "drop"} / {"kind": "dup"}."""
        now = time.monotonic()
        for idx, rule in enumerate(self.rules):
            if not self._window_open(rule, now):
                continue
            if not self._match(rule, link, method):
                continue
            if rule.kind == "drop_reply" or (
                    rule.kind == "partition" and rule.mode in ("sym", "in")):
                if self._decide(idx, rule) is not None:
                    self._record(rule.kind)
                    return {"kind": "drop"}
            elif rule.kind == "dup_reply":
                if self._decide(idx, rule) is not None:
                    self._record("dup_reply")
                    return {"kind": "dup"}
        return None

    def on_accept(self, link: str) -> float:
        """Server accepted a connection.  Returns seconds to stall before
        reading anything (0 = serve normally) — the gray-failure model: the
        TCP handshake succeeds, the peer looks alive, nothing answers."""
        now = time.monotonic()
        for idx, rule in enumerate(self.rules):
            if rule.kind != "stall" or not self._window_open(rule, now):
                continue
            if rule.link is not None and rule.link not in link:
                continue
            if self._decide(idx, rule) is not None:
                self._record("stall")
                if rule.dur is not None:
                    return max(0.0, (self._t0 + rule.at + rule.dur) - now)
                return 3600.0  # no window: stalled for the process's life
        return 0.0

    def describe(self) -> str:
        return "; ".join(r.describe() for r in self.rules)


# --------------------------------------------------------------- module API


def arm(spec: str, seed: int = 0) -> FaultSchedule:
    """Arm a schedule in THIS process (tests; env arming covers spawned
    children).  Replaces any armed schedule; returns it for assertions."""
    from ..core import rpc

    sched = FaultSchedule(spec, seed)
    rpc.set_fault_schedule(sched)
    print(f"netfault: armed seed={sched.seed} spec={spec!r}",
          file=sys.stderr, flush=True)
    return sched


def disarm():
    from ..core import rpc

    rpc.set_fault_schedule(None)


def current() -> Optional[FaultSchedule]:
    from ..core import rpc

    return rpc._netfault
