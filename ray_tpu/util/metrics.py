"""User-defined metrics: Counter / Gauge / Histogram.

Role-equivalent to the reference's ray.util.metrics
(reference: python/ray/util/metrics.py backed by the C++ OpenCensus stats
pipeline, src/ray/stats/metric.h): metric instruments are process-local and
a background flusher ships deltas to the head, which aggregates across
processes.  `list_state(kind="metrics")` (and the CLI `metrics` command)
reads the aggregate; `prometheus_text()` renders the exposition format.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_instruments: List["_Metric"] = []
_flusher_started = False


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class _Metric:
    kind = "counter"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _instruments.append(self)
        _ensure_flusher()

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self.name, "kind": self.kind,
                 "description": self.description,
                 "tags": dict(k), "value": v}
                for k, v in self._values.items()
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        k = _tags_key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_tags_key(tags)] = value


class Histogram(_Metric):
    """Fixed-boundary histogram; value snapshot ships bucket counts + sum."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries) or (
            0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10,
        )
        self._buckets: Dict[Tuple, List[float]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = _tags_key(tags)
        with self._lock:
            b = self._buckets.setdefault(
                k, [0.0] * (len(self.boundaries) + 1)
            )
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    b[i] += 1
                    break
            else:
                b[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self.name, "kind": self.kind,
                 "description": self.description, "tags": dict(k),
                 "boundaries": list(self.boundaries),
                 "buckets": list(self._buckets.get(k, [])),
                 "sum": self._sums.get(k, 0.0),
                 "count": self._counts.get(k, 0),
                 "value": self._counts.get(k, 0)}
                for k in self._counts
            ]


def _flush_once():
    from ..core.context import ctx

    if ctx.client is None:
        return
    with _registry_lock:
        instruments = list(_instruments)
    rows = []
    for m in instruments:
        rows.extend(m._snapshot())
    if rows:
        try:
            ctx.client.call_bg("metrics_report", {
                "pid": __import__("os").getpid(),
                "rows": rows,
            })
        except Exception:
            pass


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(2.0)
            _flush_once()

    threading.Thread(target=loop, daemon=True, name="metrics-flush").start()


def prometheus_text(rows: List[dict]) -> str:
    """Render aggregated metric rows in the Prometheus exposition format
    (reference: _private/prometheus_exporter.py)."""
    out = []
    seen = set()
    for r in rows:
        if r["name"] not in seen:
            seen.add(r["name"])
            if r.get("description"):
                out.append(f"# HELP {r['name']} {r['description']}")
            out.append(f"# TYPE {r['name']} {r['kind']}")
        tag_s = ",".join(f'{k}="{v}"' for k, v in r.get("tags", {}).items())
        label = f"{{{tag_s}}}" if tag_s else ""
        out.append(f"{r['name']}{label} {r['value']}")
    return "\n".join(out) + "\n"
