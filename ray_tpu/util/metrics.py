"""User-defined metrics: Counter / Gauge / Histogram.

Role-equivalent to the reference's ray.util.metrics
(reference: python/ray/util/metrics.py backed by the C++ OpenCensus stats
pipeline, src/ray/stats/metric.h): metric instruments are process-local and
a background flusher ships deltas to the head, which aggregates across
processes.  `list_state(kind="metrics")` (and the CLI `metrics` command)
reads the aggregate; `prometheus_text()` renders the exposition format.

Built-in framework metrics are namespaced ``ray_tpu_*`` (see
core/telemetry.py for the head-side set and the retained time-series
history behind ``list_state(kind="metrics_history")``).
"""

from __future__ import annotations

import atexit
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: Catalog of every built-in ``ray_tpu_*`` metric the framework emits,
#: name -> kind.  This is the contract operators wire dashboards and
#: alerts against; rtlint rule RT006 asserts the package's emitters and
#: this catalog agree (an uncataloged emission is invisible
#: infrastructure, a row nothing emits is a panel that never populates).
#: Adding a built-in metric means adding its row here in the same PR.
BUILTIN_METRICS: Dict[str, str] = {
    # scheduler / tasks (core/telemetry.py)
    "ray_tpu_scheduler_submit_to_start_seconds": "histogram",
    "ray_tpu_scheduler_queue_depth": "gauge",
    "ray_tpu_scheduler_tasks_dispatched_total": "counter",
    "ray_tpu_task_duration_seconds": "histogram",
    # object store (core/telemetry.py)
    "ray_tpu_object_store_used_bytes": "gauge",
    "ray_tpu_object_store_capacity_bytes": "gauge",
    "ray_tpu_object_store_bytes_stored_total": "gauge",
    "ray_tpu_object_store_bytes_transferred_total": "gauge",
    "ray_tpu_object_store_hit_rate": "gauge",
    # train goodput (train/telemetry.py)
    "ray_tpu_train_step_seconds": "gauge",
    "ray_tpu_train_tokens_per_sec": "gauge",
    "ray_tpu_train_mfu": "gauge",
    "ray_tpu_train_compile_seconds": "gauge",
    # serve (serve/replica.py, serve/batching.py, serve/handle.py)
    "ray_tpu_serve_request_latency_seconds": "histogram",
    "ray_tpu_serve_replica_queue_depth": "gauge",
    "ray_tpu_serve_batch_size": "histogram",
    "ray_tpu_serve_batch_queue_depth": "gauge",
    "ray_tpu_serve_replica_retries_total": "counter",
    # LLM inference engine (serve/engine.py)
    "ray_tpu_gen_tokens_total": "counter",
    "ray_tpu_gen_prefill_tokens_total": "counter",
    "ray_tpu_gen_kv_pages_in_use": "gauge",
    "ray_tpu_serve_engine_queue_depth": "gauge",
    "ray_tpu_serve_engine_active_seqs": "gauge",
    "ray_tpu_serve_engine_shed_total": "counter",
    "ray_tpu_serve_engine_completed_total": "counter",
    "ray_tpu_serve_engine_cancelled_total": "counter",
    "ray_tpu_serve_engine_ttft_seconds": "histogram",
    "ray_tpu_serve_engine_itl_seconds": "histogram",
    # multi-tenant serving plane (serve/engine.py)
    "ray_tpu_serve_prefix_cache_hits_total": "counter",
    "ray_tpu_serve_prefix_cache_pages_shared": "gauge",
    "ray_tpu_serve_adapter_evictions_total": "counter",
    "ray_tpu_serve_tenant_shed_total": "counter",
    # data (data/dataset.py)
    "ray_tpu_data_rows_total": "counter",
    "ray_tpu_data_stage_seconds_total": "counter",
    "ray_tpu_data_rows_per_sec": "gauge",
    # autoscaler (autoscaler/__init__.py)
    "ray_tpu_autoscaler_demand": "gauge",
    "ray_tpu_autoscaler_decisions_total": "counter",
    # dataplane (core/dataplane.py client-side; core/telemetry.py head-side)
    "ray_tpu_direct_calls_total": "counter",
    "ray_tpu_leased_tasks_total": "counter",
    "ray_tpu_lease_revocations_total": "counter",
    # head fault tolerance (core/telemetry.py head-side)
    "ray_tpu_head_restarts_total": "counter",
    "ray_tpu_headless_seconds": "gauge",
    "ray_tpu_resync_reports_total": "counter",
    # network fault plane (util/netfault.py injection sites; core/deadline.py
    # retry/deadline sites; core/dataplane.py quarantines)
    "ray_tpu_netfaults_injected_total": "counter",
    "ray_tpu_rpc_retries_total": "counter",
    "ray_tpu_rpc_deadline_exceeded_total": "counter",
    "ray_tpu_peer_quarantines_total": "counter",
    # logging plane (core/worker_main.py)
    "ray_tpu_logs_dropped_total": "counter",
    # tracing span plane (util/tracing.py): batched flushes + visible drops
    "ray_tpu_spans_emitted_total": "counter",
    "ray_tpu_spans_dropped_total": "counter",
    # engine step flight recorder (util/steprec.py ring; serve/engine.py
    # records; core/head.py h_engine_step_batch joins)
    "ray_tpu_step_records_flushed_total": "counter",
    "ray_tpu_step_records_dropped_total": "counter",
    "ray_tpu_engine_stall_seconds_total": "counter",
    # device-memory accounting (util/devmem.py)
    "ray_tpu_devmem_pool_bytes": "gauge",
    # on-demand profiler capture (core/worker_main.py profile handler)
    "ray_tpu_profile_captures_total": "counter",
    # health / incident plane (core/head.py wiring over util/health.py;
    # loop-lag + handler histograms are the head's self-observability)
    "ray_tpu_incidents_opened_total": "counter",
    "ray_tpu_incidents_resolved_total": "counter",
    "ray_tpu_head_loop_lag_seconds": "gauge",
    "ray_tpu_head_rpc_handler_seconds": "histogram",
    # gang training observability (util/gangrec.py ring; train/session.py
    # round records; collective/collective.py per-op timing;
    # core/head.py h_gang_round_batch joins)
    "ray_tpu_gang_rounds_flushed_total": "counter",
    "ray_tpu_gang_rounds_dropped_total": "counter",
    "ray_tpu_gang_round_skew_seconds": "histogram",
    "ray_tpu_gang_data_wait_seconds": "histogram",
    "ray_tpu_collective_op_seconds": "histogram",
    "ray_tpu_collective_bytes_total": "counter",
    # put-path contention accounting (core/object_store.py stages + lock
    # waits; core/rpc.py outbox queue delay)
    "ray_tpu_store_lock_wait_seconds": "histogram",
    "ray_tpu_put_copy_seconds": "histogram",
    "ray_tpu_rpc_outbox_delay_seconds": "histogram",
}

_registry_lock = threading.Lock()
_instruments: List["_Metric"] = []
_named: Dict[Tuple[str, str], "_Metric"] = {}  # (kind, name) -> instrument
_flusher_started = False


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class _Metric:
    kind = "counter"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (), register: bool = True):
        """``register=False`` keeps the instrument out of the process
        flusher — used by the head, which aggregates its own instruments
        directly instead of reporting to itself over RPC."""
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        if register:
            with _registry_lock:
                _instruments.append(self)
            _ensure_flusher()

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self.name, "kind": self.kind,
                 "description": self.description,
                 "tags": dict(k), "value": v}
                for k, v in self._values.items()
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        k = _tags_key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_tags_key(tags)] = value


class Histogram(_Metric):
    """Fixed-boundary histogram; value snapshot ships bucket counts + sum."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = (), register: bool = True):
        super().__init__(name, description, tag_keys, register=register)
        self.boundaries = tuple(boundaries) or (
            0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10,
        )
        self._buckets: Dict[Tuple, List[float]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = _tags_key(tags)
        with self._lock:
            b = self._buckets.setdefault(
                k, [0.0] * (len(self.boundaries) + 1)
            )
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    b[i] += 1
                    break
            else:
                b[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self.name, "kind": self.kind,
                 "description": self.description, "tags": dict(k),
                 "boundaries": list(self.boundaries),
                 "buckets": list(self._buckets.get(k, [])),
                 "sum": self._sums.get(k, 0.0),
                 "count": self._counts.get(k, 0),
                 "value": self._counts.get(k, 0)}
                for k in self._counts
            ]


# -- memoized getters (auto-instrumentation call sites) -----------------------
# Hot paths (serve request scope, data part execution) must not create a new
# instrument per call: these return one process-wide instrument per name.


def _get_named(key: Tuple[str, str], make) -> "_Metric":
    """Lookup-or-create under ONE lock hold: constructing outside the lock
    would let a racing first call register a duplicate instrument that the
    flusher then snapshots forever.  The instrument is built unregistered
    and inserted into the flusher registry only as the winner.

    First call wins: description/boundaries/tag_keys passed by LATER calls
    for the same (kind, name) are ignored, so call sites for one metric
    must agree on its shape.  A name must also stick to one kind — the
    same name as both counter and gauge would render an exposition that
    Prometheus rejects as a duplicate-name conflict."""
    with _registry_lock:
        m = _named.get(key)
        if m is None:
            m = _named[key] = make()
            _instruments.append(m)
    _ensure_flusher()
    return m


def get_counter(name: str, description: str = "",
                tag_keys: Sequence[str] = ()) -> Counter:
    return _get_named(  # type: ignore[return-value]
        ("counter", name),
        lambda: Counter(name, description, tag_keys, register=False))


def get_gauge(name: str, description: str = "",
              tag_keys: Sequence[str] = ()) -> Gauge:
    return _get_named(  # type: ignore[return-value]
        ("gauge", name),
        lambda: Gauge(name, description, tag_keys, register=False))


def get_histogram(name: str, description: str = "",
                  boundaries: Sequence[float] = (),
                  tag_keys: Sequence[str] = ()) -> Histogram:
    return _get_named(  # type: ignore[return-value]
        ("histogram", name),
        lambda: Histogram(name, description, boundaries, tag_keys,
                          register=False))


def _flush_once():
    from ..core.context import ctx

    if ctx.client is None:
        return
    with _registry_lock:
        instruments = list(_instruments)
    rows = []
    for m in instruments:
        rows.extend(m._snapshot())
    if rows:
        try:
            ctx.client.call_bg("metrics_report", {
                "pid": __import__("os").getpid(),
                "rows": rows,
            })
        except Exception:
            pass


def _flush_interval() -> float:
    try:
        from ..core.config import get_config

        return max(0.1, float(get_config().metrics_flush_interval_s))
    except Exception:
        return 2.0


def _final_flush():
    """atexit hook: ship the last window of deltas so short-lived workers
    (a task-pool worker reaped right after its task, a driver script that
    exits immediately) don't lose their final metrics."""
    try:
        _flush_once()
        from ..core.context import ctx

        # Short drain bound: a wedged head must not stall process exit.
        if ctx.client is not None:
            ctx.client.drain_bg(timeout=2.0)
    except Exception:
        pass


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(_flush_interval())
            _flush_once()

    threading.Thread(target=loop, daemon=True, name="metrics-flush").start()
    atexit.register(_final_flush)


# -- Prometheus exposition ----------------------------------------------------


def _escape_label(v) -> str:
    """Escape a label value per the exposition format: backslash, quote,
    and newline must be escaped inside the double-quoted value."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(tags: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in sorted(tags.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def prometheus_text(rows: List[dict]) -> str:
    """Render aggregated metric rows in the Prometheus exposition format
    (reference: _private/prometheus_exporter.py).  Histograms emit the full
    spec shape: cumulative ``name_bucket{le="..."}`` series ending in
    ``le="+Inf"``, plus ``name_sum`` and ``name_count``."""
    out = []
    seen = set()
    for r in rows:
        name = r["name"]
        kind = r.get("kind", "counter")
        if name not in seen:
            seen.add(name)
            if r.get("description"):
                desc = str(r["description"]).replace("\\", "\\\\") \
                                            .replace("\n", "\\n")
                out.append(f"# HELP {name} {desc}")
            out.append(f"# TYPE {name} {kind}")
        tags = r.get("tags", {})
        if kind == "histogram" and r.get("boundaries") is not None:
            buckets = list(r.get("buckets") or [])
            bounds = list(r["boundaries"])
            # Per-bucket counts -> cumulative counts per the spec.
            cum = 0.0
            for bound, n in zip(bounds, buckets):
                cum += n
                le = _label_str(tags, f'le="{_fmt(bound)}"')
                out.append(f"{name}_bucket{le} {_fmt(cum)}")
            if len(buckets) > len(bounds):
                cum += buckets[-1]
            inf = _label_str(tags, 'le="+Inf"')
            count = r.get("count", cum)
            # +Inf must equal _count even when bucket data is missing.
            out.append(f"{name}_bucket{inf} {_fmt(max(cum, count))}")
            label = _label_str(tags)
            out.append(f"{name}_sum{label} {_fmt(r.get('sum', 0.0))}")
            out.append(f"{name}_count{label} {_fmt(count)}")
        else:
            label = _label_str(tags)
            out.append(f"{name}{label} {r['value']}")
    return "\n".join(out) + "\n"
