"""Engine step flight recorder: a bounded per-process record ring.

Role-equivalent to TorchTitan's flight recorder posture (PAPERS.md) on
the serving side: the inference engine's decode loop appends ONE
fixed-size record per step (step wall, batch occupancy, admission /
eviction / shed counts, KV page usage, prefix-cache hits, adapter pins,
admission-stall span) and this module gets it to three places without
ever blocking the loop:

1. **Head ring** — records drain as one batched ``engine_step_batch``
   RPC via the client's ``call_batched`` machinery on the background
   report cadence (exactly the span plane's shape, util/tracing.py):
   they coalesce with task_done/span_batch traffic, hold bounded while
   headless, and replay at reconnect.  Ring overflow drops records —
   counted in ``ray_tpu_step_records_dropped_total``, never silent.
2. **Black box** — the last ``step_dump_records`` records are mirrored
   into a ``*.steps.log`` sidecar next to the worker's own log file on
   every flush (throttled by ``step_dump_interval_s``).  A SIGKILLed
   worker can run no exit hook, so the sidecar is written *ahead of*
   death; ``ray_tpu logs --post-mortem`` globs it up with the log tails.
3. **Tests/bench** — ``drain_buffered()`` hands back unflushed records
   for client-less harnesses (bench_serve's ``assert_step_records``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.steprec")

_ring: deque = deque()
_recent: deque = deque()  # last-N mirror for the black box (never drained)
_ring_lock = threading.Lock()
_dropped_total = 0
_warned_drop = False
_m_flushed = None
_m_dropped = None
_last_dump_t = 0.0
_dump_lock = threading.Lock()


def _cfg():
    from ..core.config import get_config

    return get_config()


def _ring_cap() -> int:
    try:
        return max(16, int(_cfg().step_ring_size))
    except Exception:
        return 2048


def _dump_cap() -> int:
    try:
        return max(0, int(_cfg().step_dump_records))
    except Exception:
        return 256


def _count_metric(which: str, n: int) -> None:
    """Lazily-resolved counters (the metrics registry lock must not sit on
    the decode loop's record path)."""
    global _m_flushed, _m_dropped
    try:
        from .metrics import get_counter

        if which == "flushed":
            if _m_flushed is None:
                _m_flushed = get_counter(
                    "ray_tpu_step_records_flushed_total",
                    "Engine step records shipped to the head "
                    "(batched flush)")
            _m_flushed.inc(n)
        else:
            if _m_dropped is None:
                _m_dropped = get_counter(
                    "ray_tpu_step_records_dropped_total",
                    "Engine step records dropped (ring overflow or flush "
                    "failure) — counted, never silent")
            _m_dropped.inc(n)
    except Exception:
        pass  # metrics must never fail the recorder


def _note_dropped(n: int, why: str) -> None:
    global _dropped_total, _warned_drop
    _dropped_total += n
    _count_metric("dropped", n)
    if not _warned_drop:
        _warned_drop = True
        logger.warning(
            "dropping engine step records (%s; %d so far, counted in "
            "ray_tpu_step_records_dropped_total) — raise step_ring_size "
            "if this persists", why, _dropped_total)


def record_step(rec: Dict[str, Any]) -> None:
    """Append one step record: buffered into the bounded process-local
    ring for the next batched flush, and mirrored into the last-N black
    box.  Overflow drops the record (counted), never blocks the caller —
    this sits on the decode loop's hot path."""
    dump_cap = _dump_cap()
    with _ring_lock:
        if dump_cap:
            if _recent.maxlen != dump_cap:
                # Config changed (or first record): rebuild the mirror.
                tail = list(_recent)[-dump_cap:]
                _recent.clear()
                _recent.__init__(tail, maxlen=dump_cap)
            _recent.append(rec)
        if len(_ring) < _ring_cap():
            _ring.append(rec)
            return
    _note_dropped(1, "step ring full")


def flush_steps(client=None) -> int:
    """Drain the ring into ONE ``engine_step_batch`` head RPC via the
    client's ``call_batched`` (coalescing with task_done / span_batch),
    and refresh the black-box sidecar.  While headless this is a NO-OP
    for the RPC half — records stay in the BOUNDED ring and the first
    post-reconnect flush replays them — but the sidecar still refreshes
    (a headless worker is exactly the one whose black box matters).
    Returns the number of records flushed to the head."""
    dump_black_box()
    if client is None:
        from ..core.context import ctx as rt_ctx

        client = rt_ctx.client
    if client is None or getattr(client, "rpc", None) is None \
            or getattr(client.rpc, "closed", False):
        return 0
    with _ring_lock:
        if not _ring:
            return 0
        batch = list(_ring)
        _ring.clear()
    try:
        client.call_batched("engine_step_batch", {"steps": batch})
    except Exception:
        _note_dropped(len(batch), "engine_step_batch flush failed")
        return 0
    _count_metric("flushed", len(batch))
    return len(batch)


def drain_buffered() -> List[Dict[str, Any]]:
    """Remove and return every buffered (not-yet-flushed) record — for
    tests and client-less harnesses (bench_serve asserts step-record
    completeness this way)."""
    with _ring_lock:
        out = list(_ring)
        _ring.clear()
    return out


def dropped_total() -> int:
    return _dropped_total


# ------------------------------------------------------------- black box


def black_box_path() -> Optional[str]:
    """Sidecar path next to this process's managed log file (None when
    the process has no spawner-assigned log, e.g. a driver).  Named
    ``<log>.steps.log`` so the post-mortem glob over ``LOG_ROOT/*/*.log``
    picks it up alongside the log tails."""
    log_path = os.environ.get("RT_LOG_PATH")
    if not log_path:
        return None
    stem = log_path[:-4] if log_path.endswith(".log") else log_path
    return stem + ".steps.log"


def dump_black_box(path: Optional[str] = None, force: bool = False) -> bool:
    """Rewrite the sidecar with the last-N records as compact JSON lines.
    Throttled by ``step_dump_interval_s`` unless ``force``.  Returns True
    when a file was written.  Never raises — a full disk must not take
    down the decode loop."""
    global _last_dump_t
    if path is None:
        path = black_box_path()
    if path is None or not _dump_cap():
        return False
    now = time.monotonic()
    with _dump_lock:
        if not force and now - _last_dump_t < \
                max(0.0, float(getattr(_cfg(), "step_dump_interval_s", 1.0))):
            return False
        with _ring_lock:
            records = list(_recent)
        if not records:
            return False
        _last_dump_t = now
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"# ray_tpu step flight recorder black box "
                        f"(pid={os.getpid()}, last {len(records)} steps)\n")
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
            os.replace(tmp, path)  # atomic: a crash mid-dump keeps the old box
            return True
        except OSError:
            return False
