"""Cluster health plane: cross-signal incident detection on the head.

Role-equivalent to the monitoring-as-part-of-the-system posture TorchTitan
(PAPERS.md) argues for, layered over this framework's existing telemetry
streams.  The head already receives everything an operator would correlate
by hand — metric snapshots, spans, task events, netfault/quarantine
counters, step records, devmem pools — so it is the natural place to run
the correlation continuously.  This module supplies three layers:

1. **Pure detectors** — free functions over bounded windows of samples.
   Every detector takes explicit inputs and a params dict and returns a
   list of *firings*; none of them touch head state, clocks, or config, so
   each one unit-tests with a seeded window and a clean one.
2. **IncidentManager** — firings become typed, deduped ``Incident``
   records with hysteresis: a firing *opens* an incident (or re-arms the
   open one, state ``active``); an incident whose key stays quiet for
   ``resolve_after_s`` *resolves*.  Resolved incidents stay in the bounded
   ring for ``ray_tpu doctor`` replay; nothing survives the head process
   (head-volatile by design, like the timeline ring).
3. **HealthEngine** — the head-facing facade: owns the sample windows,
   extracts the watched series from the aggregated metric rows each
   telemetry tick, runs every detector, and feeds the manager.  The whole
   tick is O(watched series + step records in window) and runs on the
   head loop — no locks needed, and a detector bug never breaks telemetry
   (the head wraps the tick in a try/except).

The SLO burn-rate detector follows the Google-SRE multi-window shape: the
error budget is ``1 - goal`` and an alert needs BOTH the fast and the slow
window burning above the threshold — the fast window gates detection
latency, the slow window stops a single bad batch from paging anyone.
"""

from __future__ import annotations

import itertools
import logging
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# Incident severities, ordered.  A CRIT incident trips the cluster grade.
SEV_WARN = "warn"
SEV_CRIT = "crit"

# Incident lifecycle states.
OPEN = "open"          # first firing, just noticed
ACTIVE = "active"      # fired again after opening (sustained)
RESOLVED = "resolved"  # quiet for resolve_after_s

#: Default detector thresholds.  These are detector-local tuning, not
#: cluster config: tests override them per-call, operators get the
#: windows/goals that matter via Config (health_* fields).
DEFAULTS: Dict[str, Any] = {
    # SLO burn rate (Google-SRE multi-window): burn = bad_frac / budget.
    # 14.4x burns a 30-day budget in ~2 days; 6x in ~5 days.  Both windows
    # must burn for a firing.
    "burn_fast_s": 60.0,
    "burn_slow_s": 300.0,
    "burn_fast_x": 14.4,
    "burn_slow_x": 6.0,
    "burn_min_events": 8,     # too few requests -> no signal, stay silent
    "slo_goal": 0.95,
    # Stall pressure / step-wall jitter.
    "stall_frac_warn": 0.5,   # >50% of window wall spent admission-stalled
    "stall_min_steps": 8,
    "jitter_ratio_warn": 20.0,  # p99 step wall / p50 step wall
    "jitter_min_steps": 24,
    # Partition / gray-failure suspicion (counter deltas over the window).
    "partition_min_quarantines": 1,
    "partition_min_deadlines": 3,
    # Drop pressure: ANY telemetry drops in the window are worth a WARN —
    # the rings are sized so steady state never drops.
    "drop_min": 1,
    # Devmem pool leak: strictly-growing pool across the whole window.
    "leak_min_samples": 6,
    "leak_min_bytes": 64 * 1024 * 1024,
    # Head self-observability.
    "loop_lag_warn_s": 0.5,
    "loop_lag_crit_s": 2.0,
    # Gang training plane (joined round skew profiles, util/gangrec.py).
    # Persistent straggler: the SAME rank arrives last in >= frac of the
    # windowed rounds AND its median skew is a meaningful fraction of the
    # round wall (absolute thresholds would be workload-dependent).
    "straggler_min_rounds": 6,
    "straggler_frac": 0.5,
    "straggler_skew_frac": 0.2,
    "straggler_skew_crit_frac": 1.0,  # skew >= the whole median wall
    # Data starvation: the gang's mean data wait dominates the round.
    "data_starved_frac": 0.5,
    "data_min_rounds": 6,
    # Collective desync/timeout suspicion: collective waits dominate the
    # round — some rank is late to (or wedged in) every op.
    "coll_desync_frac": 0.6,
    "coll_min_rounds": 6,
    # Trailing-window MFU regression: recent-half mean vs first-half mean.
    "mfu_drop_frac": 0.2,
    "mfu_min_rounds": 12,
}


def _params(over: Optional[dict]) -> Dict[str, Any]:
    if not over:
        return dict(DEFAULTS)
    p = dict(DEFAULTS)
    p.update(over)
    return p


class SeriesWindow:
    """Bounded (ts, value) samples of ONE metric series, appended on the
    health tick cadence.  Deltas are counter-reset tolerant: a value drop
    (process restart zeroing a counter) clamps to the post-reset value."""

    __slots__ = ("points",)

    def __init__(self, maxlen: int = 512):
        self.points: deque = deque(maxlen=maxlen)

    def add(self, ts: float, value: float) -> None:
        if self.points and self.points[-1][0] >= ts:
            return
        self.points.append((ts, float(value)))

    def latest(self) -> Optional[float]:
        return self.points[-1][1] if self.points else None

    def delta(self, now: float, window_s: float) -> float:
        """Counter increase across [now - window_s, now]."""
        if not self.points:
            return 0.0
        start = now - window_s
        base = None
        for ts, v in self.points:
            if ts >= start:
                break
            base = v
        if base is None:  # series younger than the window: first sample
            base = self.points[0][1]
        total = 0.0
        prev = base
        for ts, v in self.points:
            if ts < start:
                continue
            if v >= prev:
                total += v - prev
            else:  # counter reset
                total += v
            prev = v
        return total

    def max_over(self, now: float, window_s: float) -> Optional[float]:
        vals = [v for ts, v in self.points if ts >= now - window_s]
        return max(vals) if vals else None


class RatioWindow:
    """(ts, good, total) cumulative samples for one SLO signal (e.g. the
    count of TTFT observations under target vs all observations)."""

    __slots__ = ("points",)

    def __init__(self, maxlen: int = 512):
        self.points: deque = deque(maxlen=maxlen)

    def add(self, ts: float, good: float, total: float) -> None:
        if self.points and self.points[-1][0] >= ts:
            return
        self.points.append((ts, float(good), float(total)))

    def bad_fraction(self, now: float, window_s: float):
        """(bad_frac, events) across the window, or (None, 0) when the
        window has no delta to judge (reset-tolerant like SeriesWindow)."""
        if len(self.points) < 2:
            return None, 0
        start = now - window_s
        base = None
        for ts, g, t in self.points:
            if ts >= start:
                break
            base = (g, t)
        if base is None:
            base = (self.points[0][1], self.points[0][2])
        d_good = d_total = 0.0
        pg, pt = base
        for ts, g, t in self.points:
            if ts < start:
                continue
            if t >= pt and g >= pg:
                d_good += g - pg
                d_total += t - pt
            else:  # reset
                d_good += g
                d_total += t
            pg, pt = g, t
        if d_total <= 0:
            return None, 0
        return max(0.0, 1.0 - d_good / d_total), d_total


def firing(kind: str, key: str, severity: str, summary: str,
           **data: Any) -> Dict[str, Any]:
    """One detector hit.  ``key`` is the dedup identity: repeated firings
    with the same key feed ONE incident until it resolves."""
    return {"kind": kind, "key": key, "severity": severity,
            "summary": summary, "data": data}


# --------------------------------------------------------------- detectors


def detect_slo_burn(ratios: Dict[str, RatioWindow], now: float,
                    params: Optional[dict] = None) -> List[dict]:
    """Multi-window multi-burn-rate SLO alert per signal ('ttft', 'itl').
    Fires CRIT at the fast threshold, WARN at the slow threshold; both
    require the fast AND slow window burning (SRE workbook shape)."""
    p = _params(params)
    budget = max(1e-6, 1.0 - p["slo_goal"])
    out = []
    for signal, win in ratios.items():
        fast_bad, fast_n = win.bad_fraction(now, p["burn_fast_s"])
        slow_bad, slow_n = win.bad_fraction(now, p["burn_slow_s"])
        if fast_bad is None or slow_bad is None \
                or fast_n < p["burn_min_events"]:
            continue
        fast_burn = fast_bad / budget
        slow_burn = slow_bad / budget
        for sev, thresh in ((SEV_CRIT, p["burn_fast_x"]),
                            (SEV_WARN, p["burn_slow_x"])):
            if fast_burn >= thresh and slow_burn >= thresh:
                out.append(firing(
                    "slo_burn", f"slo_burn:{signal}", sev,
                    f"{signal} SLO burning {fast_burn:.1f}x budget "
                    f"({fast_bad:.0%} of {fast_n:.0f} requests over target "
                    f"in {p['burn_fast_s']:.0f}s window, goal "
                    f"{p['slo_goal']:.0%})",
                    signal=signal, fast_burn=round(fast_burn, 2),
                    slow_burn=round(slow_burn, 2),
                    bad_fraction=round(fast_bad, 4), events=fast_n))
                break  # report at the highest severity that matched
    return out


def detect_stall_pressure(steps: List[dict], now: float, window_s: float,
                          params: Optional[dict] = None) -> List[dict]:
    """Admission-stall pressure and step-wall jitter per engine, from
    flight-recorder step records (each carries t/engine/wall_s/stall_s)."""
    p = _params(params)
    out = []
    by_engine: Dict[str, List[dict]] = {}
    for rec in steps:
        ts = rec.get("t")
        if isinstance(ts, (int, float)) and ts >= now - window_s:
            by_engine.setdefault(str(rec.get("engine", "?")), []).append(rec)
    for eid, recs in by_engine.items():
        walls = sorted(float(r.get("wall_s", 0.0)) for r in recs)
        wall_sum = sum(walls)
        stall_sum = sum(float(r.get("stall_s", 0.0)) for r in recs)
        if len(recs) >= p["stall_min_steps"] and wall_sum > 0:
            frac = stall_sum / (wall_sum + stall_sum)
            if frac >= p["stall_frac_warn"]:
                out.append(firing(
                    "stall_pressure", f"stall:{eid}", SEV_WARN,
                    f"engine {eid} spent {frac:.0%} of the last "
                    f"{window_s:.0f}s admission-stalled "
                    f"({stall_sum:.1f}s over {len(recs)} steps)",
                    engine=eid, stall_frac=round(frac, 4),
                    stall_s=round(stall_sum, 3), steps=len(recs)))
        if len(walls) >= p["jitter_min_steps"]:
            p50 = walls[len(walls) // 2]
            p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))]
            if p50 > 0 and p99 / p50 >= p["jitter_ratio_warn"]:
                out.append(firing(
                    "step_jitter", f"jitter:{eid}", SEV_WARN,
                    f"engine {eid} step wall p99/p50 = "
                    f"{p99 * 1e3:.1f}ms/{p50 * 1e3:.1f}ms "
                    f"({p99 / p50:.0f}x) over {len(walls)} steps",
                    engine=eid, p50_s=round(p50, 6), p99_s=round(p99, 6),
                    ratio=round(p99 / p50, 1), steps=len(walls)))
    return out


def detect_partition(counters: Dict[str, SeriesWindow], now: float,
                     window_s: float,
                     params: Optional[dict] = None) -> List[dict]:
    """Partition / gray-failure suspicion from fault-counter deltas:
    peer quarantines are a hard signal (the dataplane only quarantines a
    peer after repeated failed probes); a burst of RPC deadline
    expiries corroborates when no quarantine has landed yet."""
    p = _params(params)
    deltas = {name: win.delta(now, window_s)
              for name, win in counters.items()}
    quar = deltas.get("quarantines", 0.0)
    dead = deltas.get("deadline_exceeded", 0.0)
    faults = deltas.get("netfaults", 0.0)
    retries = deltas.get("retries", 0.0)
    suspect = quar >= p["partition_min_quarantines"] \
        or dead >= p["partition_min_deadlines"]
    if not suspect:
        return []
    parts = []
    if quar:
        parts.append(f"{quar:.0f} peer quarantine(s)")
    if dead:
        parts.append(f"{dead:.0f} rpc deadline(s) exceeded")
    if faults:
        parts.append(f"{faults:.0f} injected netfault(s)")
    if retries:
        parts.append(f"{retries:.0f} rpc retr(ies)")
    return [firing(
        "partition_suspicion", "partition", SEV_CRIT,
        "network partition / gray failure suspected: "
        + ", ".join(parts) + f" in the last {window_s:.0f}s",
        deltas={k: round(v, 1) for k, v in deltas.items() if v})]


def detect_drop_pressure(counters: Dict[str, SeriesWindow], now: float,
                         window_s: float,
                         params: Optional[dict] = None) -> List[dict]:
    """Telemetry rings shedding records (spans / step records / log lines
    dropped): observability itself is degrading, which masks every other
    detector — worth its own incident."""
    p = _params(params)
    deltas = {name: win.delta(now, window_s)
              for name, win in counters.items()}
    dropped = sum(deltas.values())
    if dropped < p["drop_min"]:
        return []
    detail = ", ".join(f"{k}={v:.0f}" for k, v in deltas.items() if v)
    return [firing(
        "drop_pressure", "drops", SEV_WARN,
        f"telemetry rings dropped {dropped:.0f} record(s) in the last "
        f"{window_s:.0f}s ({detail})",
        deltas={k: round(v, 1) for k, v in deltas.items() if v})]


def detect_devmem_leak(pools: Dict[str, SeriesWindow], now: float,
                       window_s: float,
                       params: Optional[dict] = None) -> List[dict]:
    """Monotone pool growth across the whole window: a pool that only ever
    grows (every consecutive sample strictly larger) for leak_min_samples
    and gained leak_min_bytes looks like an accumulation bug, not churn."""
    p = _params(params)
    out = []
    for pool_key, win in pools.items():
        pts = [(ts, v) for ts, v in win.points if ts >= now - window_s]
        if len(pts) < p["leak_min_samples"]:
            continue
        vals = [v for _, v in pts]
        growth = vals[-1] - vals[0]
        if growth < p["leak_min_bytes"]:
            continue
        if all(b > a for a, b in zip(vals, vals[1:])):
            out.append(firing(
                "devmem_leak", f"devmem_leak:{pool_key}", SEV_WARN,
                f"device pool {pool_key} grew monotonically by "
                f"{growth / 2**20:.0f} MiB over {len(vals)} samples "
                f"({window_s:.0f}s) without ever shrinking",
                pool=pool_key, growth_bytes=int(growth),
                samples=len(vals), latest_bytes=int(vals[-1])))
    return out


def detect_head_pressure(loop_lag: SeriesWindow, now: float,
                         window_s: float,
                         params: Optional[dict] = None) -> List[dict]:
    """Head event-loop lag: the probe measures how late the periodic tick
    wakes up — sustained lag means every RPC handler is queueing behind
    something (the per-method handler histograms in the evidence say
    what)."""
    p = _params(params)
    worst = loop_lag.max_over(now, window_s)
    if worst is None or worst < p["loop_lag_warn_s"]:
        return []
    sev = SEV_CRIT if worst >= p["loop_lag_crit_s"] else SEV_WARN
    return [firing(
        "head_pressure", "head_loop_lag", sev,
        f"head event loop lagged up to {worst * 1e3:.0f}ms in the last "
        f"{window_s:.0f}s (handlers are queueing)",
        max_lag_s=round(worst, 4))]


def _profiles_by_gang(profiles: List[dict], now: float,
                      window_s: float) -> Dict[str, List[dict]]:
    by: Dict[str, List[dict]] = {}
    for pr in profiles or []:
        ts = pr.get("t")
        if isinstance(ts, (int, float)) and ts >= now - window_s:
            by.setdefault(str(pr.get("gang", "?")), []).append(pr)
    return by


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def detect_gang_straggler(profiles: List[dict], now: float, window_s: float,
                          params: Optional[dict] = None) -> List[dict]:
    """Persistent straggler per gang, from joined round skew profiles
    (util/gangrec.skew_profile rows, each carrying t/gang/round/straggler/
    skew_s/wall_s/phase): fires when the SAME rank arrives last in >=
    ``straggler_frac`` of the windowed rounds AND its median skew is >=
    ``straggler_skew_frac`` of the median round wall.  A round-robin of
    slow ranks (ordinary jitter) never fires — that is the point of the
    dominance test."""
    p = _params(params)
    out = []
    for gang, prs in _profiles_by_gang(profiles, now, window_s).items():
        if len(prs) < p["straggler_min_rounds"]:
            continue
        counts: Dict[Any, int] = {}
        for pr in prs:
            r = pr.get("straggler")
            if r is not None:
                counts[r] = counts.get(r, 0) + 1
        if not counts:
            continue
        rank, n = max(counts.items(), key=lambda kv: kv[1])
        if n / len(prs) < p["straggler_frac"]:
            continue
        mine = [pr for pr in prs if pr.get("straggler") == rank]
        med_skew = _median([float(pr.get("skew_s", 0.0)) for pr in mine])
        med_wall = _median([float(pr.get("wall_s", 0.0)) for pr in prs])
        if med_wall <= 0 or med_skew / med_wall < p["straggler_skew_frac"]:
            continue
        phases: Dict[str, int] = {}
        for pr in mine:
            ph = str(pr.get("phase") or "?")
            phases[ph] = phases.get(ph, 0) + 1
        phase = max(phases.items(), key=lambda kv: kv[1])[0]
        worst = sorted(mine, key=lambda pr: -float(pr.get("skew_s", 0.0)))[:3]
        sev = SEV_CRIT if med_skew / med_wall >= \
            p["straggler_skew_crit_frac"] else SEV_WARN
        out.append(firing(
            "gang_straggler", f"gang_straggler:{gang}", sev,
            f"gang {gang} rank {rank} straggled in {n}/{len(prs)} rounds "
            f"(median skew {med_skew * 1e3:.0f}ms = "
            f"{med_skew / med_wall:.0%} of median round wall; "
            f"slow phase: {phase})",
            gang=gang, rank=rank, phase=phase,
            skew_frac=round(med_skew / med_wall, 3),
            median_skew_s=round(med_skew, 6), rounds=len(prs),
            straggler_rounds=n,
            worst_rounds=[{
                "round": pr.get("round"), "skew_s": pr.get("skew_s"),
                "phase": pr.get("phase"), "wall_s": pr.get("wall_s"),
            } for pr in worst]))
    return out


def detect_gang_data_starvation(profiles: List[dict], now: float,
                                window_s: float,
                                params: Optional[dict] = None) -> List[dict]:
    """Data-starvation pressure per gang: the gang's mean data-wait
    fraction (profile ``data_frac``) stays above threshold — the input
    pipeline, not compute, is pacing the whole gang."""
    p = _params(params)
    out = []
    for gang, prs in _profiles_by_gang(profiles, now, window_s).items():
        fracs = [float(pr["data_frac"]) for pr in prs
                 if isinstance(pr.get("data_frac"), (int, float))]
        if len(fracs) < p["data_min_rounds"]:
            continue
        med = _median(fracs)
        if med < p["data_starved_frac"]:
            continue
        out.append(firing(
            "gang_data_starvation", f"gang_data_starvation:{gang}", SEV_WARN,
            f"gang {gang} spent a median {med:.0%} of each round waiting "
            f"on data over {len(fracs)} rounds — input pipeline is pacing "
            "the gang",
            gang=gang, data_frac=round(med, 3), rounds=len(fracs)))
    return out


def detect_gang_collective_desync(profiles: List[dict], now: float,
                                  window_s: float,
                                  params: Optional[dict] = None
                                  ) -> List[dict]:
    """Collective desync / timeout suspicion per gang: collective waits
    (profile ``coll_frac``) dominate the round — ranks spend the round
    parked inside allreduce/barrier waiting for a late or wedged peer.
    Corroborate with the straggler incident (same window) to name it."""
    p = _params(params)
    out = []
    for gang, prs in _profiles_by_gang(profiles, now, window_s).items():
        fracs = [float(pr["coll_frac"]) for pr in prs
                 if isinstance(pr.get("coll_frac"), (int, float))]
        if len(fracs) < p["coll_min_rounds"]:
            continue
        med = _median(fracs)
        if med < p["coll_desync_frac"]:
            continue
        out.append(firing(
            "gang_collective_desync", f"gang_collective_desync:{gang}",
            SEV_WARN,
            f"gang {gang} spent a median {med:.0%} of each round inside "
            f"collective waits over {len(fracs)} rounds — desync or "
            "timeout suspicion",
            gang=gang, coll_frac=round(med, 3), rounds=len(fracs)))
    return out


def detect_gang_mfu_regression(profiles: List[dict], now: float,
                               window_s: float,
                               params: Optional[dict] = None) -> List[dict]:
    """Trailing-window MFU regression per gang: the recent half of the
    window's mean MFU dropped >= ``mfu_drop_frac`` below the first
    half's.  Catches slow degradation (thermal throttling, a recovering
    rank on cold caches) that per-round skew never trips."""
    p = _params(params)
    out = []
    for gang, prs in _profiles_by_gang(profiles, now, window_s).items():
        seq = sorted(
            (pr for pr in prs if isinstance(pr.get("mfu"), (int, float))),
            key=lambda pr: pr.get("round") or 0)
        if len(seq) < p["mfu_min_rounds"]:
            continue
        half = len(seq) // 2
        base = sum(float(pr["mfu"]) for pr in seq[:half]) / half
        recent = sum(float(pr["mfu"]) for pr in seq[half:]) \
            / (len(seq) - half)
        if base <= 0:
            continue
        drop = 1.0 - recent / base
        if drop < p["mfu_drop_frac"]:
            continue
        out.append(firing(
            "gang_mfu_regression", f"gang_mfu_regression:{gang}", SEV_WARN,
            f"gang {gang} MFU regressed {drop:.0%} over the trailing "
            f"window ({base:.3f} -> {recent:.3f} across {len(seq)} rounds)",
            gang=gang, mfu_base=round(base, 4), mfu_recent=round(recent, 4),
            drop_frac=round(drop, 3), rounds=len(seq)))
    return out


# --------------------------------------------------------------- incidents


class IncidentManager:
    """Firings -> deduped Incident records with hysteresis.

    Lifecycle: a firing whose key has no open incident OPENS one (evidence
    is captured once, at open — the window that tripped the detector is
    the interesting one); further firings mark it ACTIVE and bump
    fired_count; ``resolve_after_s`` of silence RESOLVES it.  The ring
    keeps at most ``max_incidents`` records, evicting oldest-resolved
    first (open incidents are never evicted below the cap)."""

    def __init__(self, resolve_after_s: float = 20.0,
                 max_incidents: int = 256,
                 on_open: Optional[Callable[[dict], None]] = None,
                 on_resolve: Optional[Callable[[dict], None]] = None):
        self.resolve_after_s = float(resolve_after_s)
        self.max_incidents = max(8, int(max_incidents))
        self.on_open = on_open
        self.on_resolve = on_resolve
        self.incidents: "OrderedDict[str, dict]" = OrderedDict()
        self._open_by_key: Dict[str, str] = {}
        self._ids = itertools.count(1)

    def observe(self, firings: List[dict], now: Optional[float] = None,
                evidence: Optional[Callable[[dict, float], dict]] = None
                ) -> List[dict]:
        """Feed one detector pass; returns incidents opened this pass."""
        now = time.time() if now is None else now
        opened = []
        for f in firings:
            iid = self._open_by_key.get(f["key"])
            if iid is not None:
                inc = self.incidents[iid]
                inc["state"] = ACTIVE
                inc["last_fired"] = now
                inc["fired_count"] += 1
                inc["summary"] = f["summary"]
                # Severity only escalates while open (warn -> crit).
                if f["severity"] == SEV_CRIT:
                    inc["severity"] = SEV_CRIT
                inc["data"] = f["data"]
                continue
            iid = f"inc-{next(self._ids):04d}"
            inc = {
                "id": iid, "kind": f["kind"], "key": f["key"],
                "severity": f["severity"], "state": OPEN,
                "summary": f["summary"], "data": f["data"],
                "opened": now, "last_fired": now, "resolved": None,
                "fired_count": 1, "evidence": {},
            }
            if evidence is not None:
                try:
                    inc["evidence"] = evidence(f, now) or {}
                except Exception:
                    logger.exception("health: evidence capture failed")
            self.incidents[iid] = inc
            self._open_by_key[f["key"]] = iid
            opened.append(inc)
            if self.on_open is not None:
                try:
                    self.on_open(inc)
                except Exception:
                    logger.exception("health: on_open sink failed")
        self._resolve_quiet(now)
        self._trim()
        return opened

    def _resolve_quiet(self, now: float) -> None:
        for key, iid in list(self._open_by_key.items()):
            inc = self.incidents[iid]
            if now - inc["last_fired"] >= self.resolve_after_s:
                inc["state"] = RESOLVED
                inc["resolved"] = now
                del self._open_by_key[key]
                if self.on_resolve is not None:
                    try:
                        self.on_resolve(inc)
                    except Exception:
                        logger.exception("health: on_resolve sink failed")

    def _trim(self) -> None:
        while len(self.incidents) > self.max_incidents:
            victim = next((i for i, inc in self.incidents.items()
                           if inc["state"] == RESOLVED), None)
            if victim is None:  # all open (pathological): drop oldest
                victim = next(iter(self.incidents))
                self._open_by_key.pop(self.incidents[victim]["key"], None)
            del self.incidents[victim]

    def open_count(self) -> int:
        return len(self._open_by_key)

    def grade(self) -> str:
        """OK (nothing open) / WARN (open warns) / CRIT (open crits)."""
        worst = "OK"
        for iid in self._open_by_key.values():
            if self.incidents[iid]["severity"] == SEV_CRIT:
                return "CRIT"
            worst = "WARN"
        return worst

    def snapshot(self) -> List[dict]:
        """Newest-first copies, wire-safe (plain dicts/scalars only)."""
        return [dict(inc) for inc in reversed(self.incidents.values())]

    def get(self, id_prefix: str) -> List[dict]:
        return [dict(inc) for iid, inc in self.incidents.items()
                if iid.startswith(id_prefix)]


# ------------------------------------------------------------ head facade


#: metric name -> short window key for the partition detector.
_FAULT_COUNTERS = {
    "ray_tpu_peer_quarantines_total": "quarantines",
    "ray_tpu_rpc_deadline_exceeded_total": "deadline_exceeded",
    "ray_tpu_rpc_retries_total": "retries",
    "ray_tpu_netfaults_injected_total": "netfaults",
}

#: metric name -> short window key for the drop-pressure detector.
_DROP_COUNTERS = {
    "ray_tpu_spans_dropped_total": "spans",
    "ray_tpu_step_records_dropped_total": "step_records",
    "ray_tpu_gang_rounds_dropped_total": "gang_rounds",
    "ray_tpu_logs_dropped_total": "logs",
}

#: serve SLO signals: latency histogram -> ratio-window key.
_SLO_HISTOGRAMS = {
    "ray_tpu_serve_engine_ttft_seconds": "ttft",
    "ray_tpu_serve_engine_itl_seconds": "itl",
}


def _sum_rows(rows: List[dict], name: str) -> Optional[float]:
    """Sum a counter/gauge across every tag combination and source."""
    total, seen = 0.0, False
    for row in rows:
        if row.get("name") == name \
                and isinstance(row.get("value"), (int, float)):
            total += row["value"]
            seen = True
    return total if seen else None


def _histogram_good_total(rows: List[dict], name: str, target_s: float):
    """Cumulative (observations <= target, all observations) for one
    latency histogram, summed across tags; the bucket whose upper bound
    covers target_s defines 'good' (conservative: first bound >= target)."""
    good = total = 0.0
    seen = False
    for row in rows:
        if row.get("name") != name or "buckets" not in row:
            continue
        bounds = row.get("boundaries") or ()
        buckets = row.get("buckets") or ()
        count = float(row.get("count", 0))
        idx = next((i for i, b in enumerate(bounds) if b >= target_s), None)
        cum = 0.0
        for i, n in enumerate(buckets):
            cum += n
            if idx is not None and i == idx:
                break
        good += cum if idx is not None else count
        total += count
        seen = True
    return (good, total) if seen else None


class HealthEngine:
    """Owns the sample windows + IncidentManager; ``tick()`` runs on the
    head loop at the telemetry cadence.  All inputs arrive as plain data
    gathered by the head — this class never reaches into head state."""

    def __init__(self, window_s: float = 30.0, resolve_after_s: float = 20.0,
                 max_incidents: int = 256, params: Optional[dict] = None,
                 on_open: Optional[Callable[[dict], None]] = None,
                 on_resolve: Optional[Callable[[dict], None]] = None):
        self.window_s = float(window_s)
        self.params = _params(params)
        self.manager = IncidentManager(
            resolve_after_s=resolve_after_s, max_incidents=max_incidents,
            on_open=on_open, on_resolve=on_resolve)
        self._faults: Dict[str, SeriesWindow] = {
            k: SeriesWindow() for k in _FAULT_COUNTERS.values()}
        self._drops: Dict[str, SeriesWindow] = {
            k: SeriesWindow() for k in _DROP_COUNTERS.values()}
        self._ratios: Dict[str, RatioWindow] = {}
        self._pools: Dict[str, SeriesWindow] = {}
        self._loop_lag = SeriesWindow()
        self.last_tick = 0.0
        self.ticks = 0

    def tick(self, now: float, rows: List[dict], steps: List[dict],
             devmem: Dict[str, dict], loop_lag_s: float,
             slo_targets: Optional[Dict[str, float]] = None,
             evidence: Optional[Callable[[dict, float], dict]] = None,
             gang_profiles: Optional[List[dict]] = None
             ) -> List[dict]:
        """One detector pass; returns incidents opened this pass."""
        self.last_tick = now
        self.ticks += 1
        for name, key in _FAULT_COUNTERS.items():
            v = _sum_rows(rows, name)
            if v is not None:
                self._faults[key].add(now, v)
        for name, key in _DROP_COUNTERS.items():
            v = _sum_rows(rows, name)
            if v is not None:
                self._drops[key].add(now, v)
        targets = slo_targets or {}
        for name, signal in _SLO_HISTOGRAMS.items():
            target = targets.get(signal)
            if not target or target <= 0:
                continue
            gt = _histogram_good_total(rows, name, target)
            if gt is not None:
                self._ratios.setdefault(signal, RatioWindow()).add(
                    now, gt[0], gt[1])
        for pool_key, size in self._pool_sizes(devmem).items():
            self._pools.setdefault(pool_key, SeriesWindow()).add(now, size)
        self._loop_lag.add(now, float(loop_lag_s))

        w, p = self.window_s, self.params
        firings: List[dict] = []
        firings += detect_slo_burn(self._ratios, now, p)
        firings += detect_stall_pressure(steps, now, w, p)
        firings += detect_partition(self._faults, now, w, p)
        firings += detect_drop_pressure(self._drops, now, w, p)
        firings += detect_devmem_leak(self._pools, now, max(w * 4, 60.0), p)
        firings += detect_head_pressure(self._loop_lag, now, w, p)
        if gang_profiles:
            firings += detect_gang_straggler(gang_profiles, now, w, p)
            firings += detect_gang_data_starvation(gang_profiles, now, w, p)
            firings += detect_gang_collective_desync(
                gang_profiles, now, w, p)
            firings += detect_gang_mfu_regression(gang_profiles, now, w, p)
        return self.manager.observe(firings, now, evidence)

    @staticmethod
    def _pool_sizes(devmem: Dict[str, dict]) -> Dict[str, float]:
        """Flatten devmem reports ({pid: {devmem: {pools: {name: info}}}})
        into {'pid:pool': bytes}."""
        out: Dict[str, float] = {}
        for pid, report in (devmem or {}).items():
            pools = ((report or {}).get("devmem") or {}).get("pools") or {}
            for pool, info in pools.items():
                size = info.get("bytes") if isinstance(info, dict) else info
                if isinstance(size, (int, float)):
                    out[f"{pid}:{pool}"] = float(size)
        return out
