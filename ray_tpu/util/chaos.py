"""Chaos-testing utilities: kill workers/nodes on a cadence.

Role-equivalent to the reference's fault-injection test tooling
(reference: python/ray/_private/test_utils.py:1433 ResourceKillerActor,
:1500 NodeKillerBase, :1597 WorkerKillerActor; the release chaos harness
at release/nightly_tests/setup_chaos.py) — used by resilience tests and
available to users who want to soak their own pipelines against failures.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Optional

import ray_tpu


class WorkerKiller:
    """SIGKILLs a random busy worker every ``interval_s`` until stopped.

    Runs in the driver (it needs os.kill on local pids; remote workers die
    through their node daemon's kill route when the head requests it — for
    cross-node chaos use NodeKiller).  Retriable tasks should still
    complete; the kill count is the assertion hook.
    """

    def __init__(self, interval_s: float = 1.0, seed: int = 0,
                 states: tuple = ("leased",)):
        self.interval_s = interval_s
        self.states = states
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills = 0

    def _loop(self):
        from ray_tpu.core.context import ctx

        local_node = (
            ctx.client.node_id.hex()
            if ctx.client and ctx.client.node_id else None
        )
        while not self._stop.wait(self.interval_s):
            try:
                workers = ctx.client.call(
                    "list_state", {"kind": "workers"}
                )["items"]
            except Exception:
                continue
            busy = [
                w for w in workers
                if w.get("state") in self.states and w.get("pid")
                # os.kill is only valid for pids this host owns: never
                # signal a pid reported by another node's daemon.
                and (local_node is None or w.get("node_id") == local_node)
            ]
            if not busy:
                continue
            victim = self._rng.choice(busy)
            try:
                os.kill(victim["pid"], signal.SIGKILL)
                self.kills += 1
            except (ProcessLookupError, PermissionError):
                pass

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(
            target=self._loop, name="worker-killer", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self.kills

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class NodeKiller:
    """Removes random non-head nodes from a ``cluster_utils.Cluster`` on a
    cadence (reference: NodeKillerBase kills raylets) — exercises task
    re-scheduling, object reconstruction, and PG bundle re-placement."""

    def __init__(self, cluster, interval_s: float = 2.0, seed: int = 0,
                 max_kills: Optional[int] = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills = 0

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            nodes = list(getattr(self.cluster, "nodes", []) or [])
            if not nodes:
                continue
            victim = self._rng.choice(nodes)
            try:
                self.cluster.remove_node(victim)
                self.kills += 1
            except Exception:
                pass

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(
            target=self._loop, name="node-killer", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self.kills

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class PreemptionInjector:
    """Announced preemptions: SIGTERMs random non-head node daemons of a
    ``cluster_utils.Cluster`` on a cadence, leaving each node its drain
    grace window (vs NodeKiller's instant kill).  Models spot/maintenance
    preemption — the dominant real-world TPU failure: the node reports
    DRAINING, training gangs get the should_checkpoint() signal, and the
    node dies only after the grace period.

    ``delay_s`` postpones the first preemption (let the workload reach
    steady state); ``max_preemptions`` bounds the blast radius so a soak
    can assert recovery rather than starve the cluster.
    """

    def __init__(self, cluster, interval_s: float = 5.0, seed: int = 0,
                 max_preemptions: Optional[int] = 1,
                 delay_s: float = 0.0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.delay_s = delay_s
        self.max_preemptions = max_preemptions
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.preemptions = 0
        self.preempted: list = []  # NodeHandles, in preemption order

    def preempt_one(self) -> bool:
        """Preempt one random remaining node now.  Returns False when the
        cluster has no non-head nodes left."""
        nodes = list(getattr(self.cluster, "nodes", []) or [])
        if not nodes:
            return False
        victim = self._rng.choice(nodes)
        try:
            self.cluster.preempt_node(victim)
        except Exception:
            return False
        self.preemptions += 1
        self.preempted.append(victim)
        return True

    def _loop(self):
        if self.delay_s and self._stop.wait(self.delay_s):
            return
        while True:
            if (self.max_preemptions is not None
                    and self.preemptions >= self.max_preemptions):
                return
            self.preempt_one()
            if self._stop.wait(self.interval_s):
                return

    def start(self) -> "PreemptionInjector":
        self._thread = threading.Thread(
            target=self._loop, name="preemption-injector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self.preemptions

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class HeadKillInjector:
    """SIGKILLs an :class:`~ray_tpu.cluster_utils.ExternalHead` on a cadence
    and restarts it after a configurable outage window — the control-plane
    crash drill (reference: the GCS FT release tests kill the GCS process
    under load and assert raylets/workers resync).  Each cycle is
    kill → outage_s of headless cluster → restart-with-same-identity;
    nodes/workers ride their reconnect loops, drivers re-register, and the
    assertion hook is ``kills`` plus whatever invariants the workload
    checks (e.g. zero failed direct calls).

    ``delay_s`` postpones the first kill (let the workload reach steady
    state); ``max_kills`` bounds the blast radius so a soak asserts
    recovery rather than an endless outage.
    """

    def __init__(self, head, interval_s: float = 5.0,
                 outage_s: float = 1.0, max_kills: Optional[int] = 1,
                 delay_s: float = 0.0):
        self.head = head
        self.interval_s = interval_s
        self.outage_s = outage_s
        self.max_kills = max_kills
        self.delay_s = delay_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills = 0

    def kill_once(self) -> bool:
        """One full kill→outage→restart cycle, synchronously."""
        try:
            self.head.kill()
        except Exception:
            return False
        self.kills += 1
        self._stop.wait(self.outage_s)
        self.head.restart()
        return True

    def _loop(self):
        if self.delay_s and self._stop.wait(self.delay_s):
            return
        while True:
            if (self.max_kills is not None
                    and self.kills >= self.max_kills):
                return
            self.kill_once()
            if self._stop.wait(self.interval_s):
                return

    def start(self) -> "HeadKillInjector":
        self._thread = threading.Thread(
            target=self._loop, name="head-kill-injector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60)
        return self.kills

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class StragglerSchedule:
    """A parsed, seeded slow-rank schedule (the training-plane analogue of
    util/netfault.py's FaultSchedule): ONE gang rank — chosen by the seed —
    runs a fixed per-phase delay inside an arm-relative time window, and
    every other rank runs clean.  The gang observability plane must then
    name that rank (and the injected phase) in its straggler incident, and
    the incident must resolve once the window closes.

    Spec DSL — ``key=val`` pairs, comma-separated::

        phase=data,ms=300,ranks=4,dur=6      # seeded rank of 4, +300ms per
                                             # data fetch, for 6s from arm
        phase=compute,ms=150,rank=2          # explicit rank, no window

    Keys: ``phase`` is the training phase to slow (``data`` — inside the
    dataset-shard iterator, ``compute`` — at report() entry, ``checkpoint``
    — inside checkpoint staging).  ``ms`` is the added delay per injection
    point.  ``ranks`` is the gang world size the seeded rank is drawn from
    (``rank=`` pins it explicitly instead).  ``at``/``dur`` bound the
    schedule to an arm-relative window (seconds) — a bounded window is how
    chaos tests assert the incident RESOLVES after heal.

    Armed two ways, mirroring netfault: ``RT_CHAOS_STRAGGLER`` +
    ``RT_CHAOS_SEED`` in the environment (children inherit, so one export
    covers a spawned gang) or :func:`arm_straggler` in-process.  Zero
    overhead when off: the injection sites check one module global against
    ``None`` and touch nothing else.
    """

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self.phase = "data"
        self.ms = 100.0
        self.at = 0.0
        self.dur: Optional[float] = None
        rank: Optional[int] = None
        ranks = 1
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            if key == "phase":
                if val not in ("data", "compute", "checkpoint"):
                    raise ValueError(
                        f"straggler: unknown phase {val!r} "
                        "(data|compute|checkpoint)")
                self.phase = val
            elif key == "ms":
                self.ms = float(val)
            elif key == "rank":
                rank = int(val)
            elif key == "ranks":
                ranks = int(val)
            elif key == "at":
                self.at = float(val)
            elif key == "dur":
                self.dur = float(val)
            else:
                raise ValueError(f"straggler: unknown spec key {key!r}")
        # Seeded rank choice — chaos_soak rotates the seed so every soak
        # iteration slows a different rank, and a failure replays from the
        # printed seed.
        self.rank = rank if rank is not None \
            else random.Random(self.seed).randrange(max(1, ranks))
        self._t0 = time.monotonic()
        self.delays = 0  # injections performed (assertion hook)

    def delay_s(self, phase: str, rank: int) -> float:
        if rank != self.rank or phase != self.phase:
            return 0.0
        t = time.monotonic() - self._t0
        if t < self.at or (self.dur is not None and t >= self.at + self.dur):
            return 0.0
        return self.ms / 1000.0

    def describe(self) -> str:
        win = "" if self.dur is None else f" at={self.at} dur={self.dur}"
        return (f"straggler rank={self.rank} phase={self.phase} "
                f"ms={self.ms:g}{win}")


_straggler: Optional[StragglerSchedule] = None
_straggler_env_checked = False


def arm_straggler(spec: str, seed: int = 0) -> StragglerSchedule:
    """Arm a straggler schedule in THIS process (tests; env arming covers
    spawned ranks).  Replaces any armed schedule; returns it for
    assertions."""
    global _straggler
    _straggler = StragglerSchedule(spec, seed)
    print(f"chaos: armed {_straggler.describe()} seed={seed}", flush=True)
    return _straggler


def disarm_straggler() -> None:
    global _straggler
    _straggler = None


def maybe_straggle(phase: str, rank: int) -> float:
    """Injection hook the train session's phase paths call.  Sleeps the
    scheduled delay when THIS (rank, phase) is the victim inside the arm
    window; free when nothing is armed (one global None-check after the
    lazy one-time env probe)."""
    global _straggler, _straggler_env_checked
    s = _straggler
    if s is None:
        if _straggler_env_checked:
            return 0.0
        _straggler_env_checked = True
        spec = os.environ.get("RT_CHAOS_STRAGGLER")
        if not spec:
            return 0.0
        s = _straggler = StragglerSchedule(
            spec, int(os.environ.get("RT_CHAOS_SEED", "0") or 0))
        print(f"chaos: armed {s.describe()} (env)", flush=True)
    d = s.delay_s(phase, rank)
    if d > 0:
        s.delays += 1
        time.sleep(d)
    return d


def run_under_chaos(fn, *, interval_s: float = 0.5, timeout_s: float = 60.0,
                    seed: int = 0):
    """Run ``fn()`` while a WorkerKiller fires; returns (result, kills).
    The canonical soak shape (reference: chaos tests wrap a workload with
    setup_chaos).  ``timeout_s`` bounds a HUNG workload — the exact
    failure a chaos soak exists to catch — by running it on a worker
    thread; on timeout the thread is abandoned (daemonic) and
    TimeoutError raised."""
    killer = WorkerKiller(interval_s=interval_s, seed=seed).start()
    box: dict = {}

    def target():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            box["error"] = e

    t = threading.Thread(target=target, name="chaos-workload", daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    try:
        if t.is_alive():
            raise TimeoutError(
                f"workload still running after {timeout_s}s under chaos"
            )
        if "error" in box:
            raise box["error"]
        return box["result"], killer.kills
    finally:
        killer.stop()
