"""ray_tpu.util: metrics, state helpers (reference: ray.util)."""

from . import metrics

__all__ = ["metrics"]
