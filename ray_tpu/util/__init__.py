"""ray_tpu.util: metrics, actor pools, queues, state helpers
(reference: ray.util)."""

from . import metrics
from .actor_pool import ActorPool
from .queue import Empty, Full, Queue

__all__ = ["metrics", "ActorPool", "Queue", "Empty", "Full"]
