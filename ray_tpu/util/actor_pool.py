"""ActorPool: load-balance a stream of work over a fixed set of actors.

Role-equivalent to the reference's ray.util.ActorPool (reference:
python/ray/util/actor_pool.py — map/map_unordered/submit/get_next over a
list of actor handles, idle actors reused as results drain).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    """A pool of actor handles fed by `fn(actor, value) -> ObjectRef`.

    Ordered consumption (`map`/`get_next`) buffers out-of-order completions
    until their turn; unordered consumption yields whatever finishes first.
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        # ref -> (actor, submission index)
        self._inflight: dict = {}
        self._next_submit = 0   # next submission index to assign
        self._next_yield = 0    # next index an ordered get returns
        self._ready_ordered: dict = {}  # index -> value (completed early)
        # Indices already handed out by get_next_unordered: ordered gets
        # skip them (reference: ActorPool tracks returned futures so the
        # two consumption modes can interleave mid-stream).
        self._consumed_unordered: set = set()

    # -- submission ----------------------------------------------------------

    def has_free(self) -> bool:
        return bool(self._idle)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Dispatch one work item to an idle actor (raises when none —
        check has_free(), or use map which interleaves automatically)."""
        if not self._idle:
            raise RuntimeError("no idle actors; drain results first")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._inflight[ref] = (actor, self._next_submit)
        self._next_submit += 1

    def push(self, actor: Any) -> None:
        """Return an external actor to the pool (reference: push)."""
        self._idle.append(actor)

    def pop_idle(self) -> Any:
        if not self._idle:
            raise RuntimeError("no idle actors")
        return self._idle.pop()

    # -- consumption ---------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._inflight) or bool(self._ready_ordered)

    def _wait_one(self, timeout: float):
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        actor, idx = self._inflight.pop(ref)
        self._idle.append(actor)
        return idx, ray_tpu.get(ref)

    def _maybe_reset(self):
        # Fully drained: restart index bookkeeping (keeps the skip set
        # from growing across independent map phases).
        if not self._inflight and not self._ready_ordered:
            self._next_submit = 0
            self._next_yield = 0
            self._consumed_unordered.clear()

    def get_next_unordered(self, timeout: float = 3600.0) -> Any:
        if self._ready_ordered:
            # Buffered by an earlier ordered wait: drain those first.
            idx = next(iter(self._ready_ordered))
            value = self._ready_ordered.pop(idx)
            self._consumed_unordered.add(idx)
            self._maybe_reset()
            return value
        if not self._inflight:
            raise StopIteration("nothing in flight")
        idx, value = self._wait_one(timeout)
        self._consumed_unordered.add(idx)
        self._maybe_reset()
        return value

    def get_next(self, timeout: float = 3600.0) -> Any:
        """Next result in SUBMISSION order (buffers later completions;
        indices an interleaved get_next_unordered already returned are
        skipped).  ``timeout`` bounds the WHOLE call, not each internal
        wait."""
        import time

        deadline = time.monotonic() + timeout
        while self._next_yield in self._consumed_unordered:
            self._consumed_unordered.discard(self._next_yield)
            self._next_yield += 1
        target = self._next_yield
        while target not in self._ready_ordered:
            if not self._inflight:
                raise StopIteration("nothing in flight")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("no result within timeout")
            self._ready_ordered.update([self._wait_one(remaining)])
        self._next_yield += 1
        value = self._ready_ordered.pop(target)
        self._maybe_reset()
        return value

    # -- bulk ----------------------------------------------------------------

    def _map_impl(self, fn, values, ordered: bool) -> Iterator[Any]:
        it = iter(values)
        exhausted = False
        while True:
            while not exhausted and self._idle:
                try:
                    v = next(it)
                except StopIteration:
                    exhausted = True
                    break
                self.submit(fn, v)
            if not self.has_next():
                return
            yield self.get_next() if ordered else self.get_next_unordered()

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Ordered results; work interleaves with consumption (reference:
        map — lazy, so an unconsumed iterator submits nothing)."""
        return self._map_impl(fn, values, ordered=True)

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        return self._map_impl(fn, values, ordered=False)
