"""Device-memory accounting: where did the HBM go, by named pool.

Role-equivalent to the reference's per-node GPU/object-store memory
panels (reference: dashboard memory view + `ray memory`), TPU-native:
the raw totals come from ``jax.local_devices()[i].memory_stats()`` (XLA's
allocator counters — absent on the CPU backend) and ``jax.live_arrays()``
(present on every backend), and the *attribution* comes from a
process-local registry of named byte-counting callables that the owners
of big device allocations register themselves:

    devmem.register_pool("kv_pool", lambda: k.nbytes + v.nbytes)

``snapshot()`` joins both: per-device allocator stats, live-array bytes,
per-pool bytes, the remainder as ``other`` — so the pools always sum to
the live total — plus compile observability (per-program jit trace
counts from ``models.paged.trace_count`` and the wall clock of the calls
that triggered them, recorded by the engine via :func:`record_compile`).

Workers ship ``maybe_snapshot()`` to the head on the metrics cadence
(``devmem_report``); the head joins the latest per-worker snapshot into
``list_state(kind="devmem")`` for ``ray_tpu top`` / ``status`` and the
dashboard.  Import of jax is never forced: a worker that hasn't touched
jax reports nothing.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_pools: Dict[str, Callable[[], int]] = {}
_compiles: Dict[str, Dict[str, float]] = {}  # program -> {count, wall_s}
_m_pool_bytes = None


def register_pool(name: str, nbytes_fn: Callable[[], int]) -> None:
    """Attribute device bytes to ``name``.  ``nbytes_fn`` is called at
    snapshot time and must be cheap and host-only (no device sync); a
    raising fn reports 0 for that pool rather than failing the snapshot.
    Re-registering replaces (an engine rebuild supersedes its pools)."""
    with _lock:
        _pools[name] = nbytes_fn


def unregister_pool(name: str) -> None:
    with _lock:
        _pools.pop(name, None)


def record_compile(program: str, wall_s: float) -> None:
    """Note one jit compile: ``wall_s`` is the wall clock of the call
    that triggered the trace (the engine compares ``trace_count`` before
    and after each program call, so the measured wall IS the user-visible
    compile stall)."""
    with _lock:
        row = _compiles.setdefault(program, {"count": 0, "wall_s": 0.0})
        row["count"] += 1
        row["wall_s"] += float(wall_s)


def compile_stats() -> Dict[str, Dict[str, float]]:
    with _lock:
        return {k: dict(v) for k, v in _compiles.items()}


def _device_stats() -> list:
    """Per-device allocator counters; [] on backends without them (CPU)."""
    import jax

    out = []
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append({
            "device": i,
            "platform": getattr(dev, "platform", "unknown"),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


def snapshot() -> Dict[str, Any]:
    """One attribution snapshot.  Invariant the tests hold: the ``pools``
    values (including ``other``) sum to ``live_bytes`` exactly."""
    import jax

    live = 0
    for arr in jax.live_arrays():
        try:
            if not arr.is_deleted():
                live += int(arr.nbytes)
        except Exception:
            continue
    with _lock:
        fns = dict(_pools)
    pools: Dict[str, int] = {}
    for name, fn in fns.items():
        try:
            pools[name] = max(0, int(fn()))
        except Exception:
            pools[name] = 0
    named = sum(pools.values())
    # Attribution is bounded by what is actually live: a stale pool fn
    # (engine torn down mid-snapshot) must not drive "other" negative.
    if named > live:
        scale = live / named if named else 0.0
        pools = {k: int(v * scale) for k, v in pools.items()}
        named = sum(pools.values())
    pools["other"] = live - named
    snap = {
        "time": time.time(),
        "live_bytes": live,
        "pools": pools,
        "devices": _device_stats(),
        "compiles": compile_stats(),
    }
    try:
        # The jitguard registry is the superset view: the paged programs
        # plus any learner/kernel that joined (models.paged.trace_counts
        # is an alias over the same counters).
        from ..devtools import jitguard as _jitguard

        snap["trace_counts"] = _jitguard.counts()
    except Exception:
        snap["trace_counts"] = {}
    _set_gauges(pools)
    return snap


def maybe_snapshot() -> Optional[Dict[str, Any]]:
    """A snapshot IF this process has already imported jax (never force
    the import — that would drag the XLA runtime into every worker)."""
    if "jax" not in sys.modules:
        return None
    try:
        return snapshot()
    except Exception:
        return None


def _set_gauges(pools: Dict[str, int]) -> None:
    global _m_pool_bytes
    try:
        from .metrics import get_gauge

        if _m_pool_bytes is None:
            _m_pool_bytes = get_gauge(
                "ray_tpu_devmem_pool_bytes",
                "Live device bytes attributed to each named pool",
                tag_keys=("pool",))
        for name, nbytes in pools.items():
            _m_pool_bytes.set(nbytes, tags={"pool": name})
    except Exception:
        pass  # metrics must never fail the snapshot
