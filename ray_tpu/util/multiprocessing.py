"""multiprocessing.Pool API over cluster tasks.

Role-equivalent to the reference's ray.util.multiprocessing (reference:
python/ray/util/multiprocessing/pool.py — a drop-in Pool whose workers are
actors, so existing multiprocessing code scales past one machine).  Here
work ships as plain tasks with chunking: the scheduler's worker pool
already provides process reuse, so no dedicated actor fleet is needed.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


@ray_tpu.remote
def _run_chunk(fn_blob: bytes, chunk: List[tuple], star: bool) -> List[Any]:
    import cloudpickle

    fn = cloudpickle.loads(fn_blob)
    return [fn(*args) if star else fn(args[0]) for args in chunk]


@ray_tpu.remote
def _apply_one(blob: bytes, args: tuple) -> List[Any]:
    import cloudpickle

    fn, kwds = cloudpickle.loads(blob)
    return [fn(*args, **kwds)]


class AsyncResult:
    """multiprocessing.pool.AsyncResult surface over object refs."""

    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None) -> Any:
        chunks = ray_tpu.get(self._refs,
                             timeout=-1.0 if timeout is None else timeout)
        flat = [v for chunk in chunks for v in chunk]
        return flat[0] if self._single else flat

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=-1.0 if timeout is None else timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result not ready")
        try:
            self.get(timeout=1.0)
            return True
        except Exception:  # noqa: BLE001 — mirrors multiprocessing
            return False


class Pool:
    """Drop-in multiprocessing.Pool: map/starmap/apply/imap + async
    variants.  `processes` bounds in-flight chunks (defaults to the
    cluster's CPU count at first use)."""

    _FN_CACHE_MAX = 32

    def __init__(self, processes: Optional[int] = None):
        self._processes = processes
        self._closed = False
        self._fn_cache: dict = {}
        # Refs of submitted work, so join() can block until completion;
        # pruned opportunistically to keep long-lived pools bounded.
        self._inflight: List[Any] = []

    def _parallelism(self) -> int:
        if self._processes is None:
            # Resolve once at first use (the submission hot path must not
            # pay a cluster RPC per map call).
            try:
                from ray_tpu.core.context import ctx

                nodes = ctx.client.call("list_state",
                                        {"kind": "nodes"})["items"]
                total = int(sum(
                    n.get("resources", {}).get("CPU", 0) for n in nodes))
                self._processes = max(total, 1)
            except Exception:  # noqa: BLE001 — sane default off-cluster
                self._processes = 4
        return self._processes

    def _blob(self, fn: Callable) -> bytes:
        # Keyed by the function OBJECT (the dict entry keeps it alive):
        # an id()-keyed cache serves stale blobs after CPython reuses a
        # collected function's id — silent wrong results.
        try:
            blob = self._fn_cache.get(fn)
        except TypeError:  # unhashable callable
            import cloudpickle

            return cloudpickle.dumps(fn)
        if blob is None:
            import cloudpickle

            blob = self._fn_cache[fn] = cloudpickle.dumps(fn)
            while len(self._fn_cache) > self._FN_CACHE_MAX:
                self._fn_cache.pop(next(iter(self._fn_cache)))
        return blob

    def _track(self, refs: List[Any]) -> None:
        self._inflight.extend(refs)
        if len(self._inflight) > 256:  # drop completed work's refs
            done, rest = ray_tpu.wait(
                self._inflight, num_returns=len(self._inflight), timeout=0)
            self._inflight = list(rest)

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")
        if not ray_tpu.is_initialized():
            ray_tpu.init()

    def _chunks(self, items: List[tuple], chunksize: Optional[int]):
        if chunksize is None:
            # multiprocessing's heuristic: ~4 chunks per worker.
            chunksize = max(1, len(items) // (self._parallelism() * 4))
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    # -- sync ----------------------------------------------------------------

    def apply(self, fn: Callable, args: tuple = (),
              kwds: Optional[dict] = None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def map(self, fn: Callable, iterable: Iterable[Any],
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(fn, iterable, chunksize).get()

    # -- async ---------------------------------------------------------------

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check_open()
        import cloudpickle

        blob = cloudpickle.dumps((fn, dict(kwds or {})))
        refs = [_apply_one.remote(blob, tuple(args))]
        self._track(refs)
        return AsyncResult(refs, single=True)

    def map_async(self, fn: Callable, iterable: Iterable[Any],
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        items = [(v,) for v in iterable]
        blob = self._blob(fn)
        refs = [_run_chunk.remote(blob, c, False)
                for c in self._chunks(items, chunksize)]
        self._track(refs)
        return AsyncResult(refs)

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        items = [tuple(v) for v in iterable]
        blob = self._blob(fn)
        refs = [_run_chunk.remote(blob, c, True)
                for c in self._chunks(items, chunksize)]
        self._track(refs)
        return AsyncResult(refs)

    # -- streaming -----------------------------------------------------------

    @staticmethod
    def _chunk_iter(iterable, size: int):
        """Lazily batch an iterable — imap must consume on demand
        (an infinite generator is legal input)."""
        buf: List[tuple] = []
        for v in iterable:
            buf.append((v,))
            if len(buf) >= size:
                yield buf
                buf = []
        if buf:
            yield buf

    def imap(self, fn: Callable, iterable: Iterable[Any],
             chunksize: int = 1) -> Iterator[Any]:
        self._check_open()
        blob = self._blob(fn)
        window = self._parallelism() * 2
        pending: List[Any] = []
        it = self._chunk_iter(iterable, chunksize)
        exhausted = False
        while True:
            while not exhausted and len(pending) < window:
                try:
                    pending.append(_run_chunk.remote(blob, next(it), False))
                except StopIteration:
                    exhausted = True
            if not pending:
                return
            for v in ray_tpu.get(pending.pop(0)):
                yield v

    def imap_unordered(self, fn: Callable, iterable: Iterable[Any],
                       chunksize: int = 1) -> Iterator[Any]:
        self._check_open()
        blob = self._blob(fn)
        window = self._parallelism() * 2
        pending: List[Any] = []
        it = self._chunk_iter(iterable, chunksize)
        exhausted = False
        while True:
            while not exhausted and len(pending) < window:
                try:
                    pending.append(_run_chunk.remote(blob, next(it), False))
                except StopIteration:
                    exhausted = True
            if not pending:
                return
            ready, rest = ray_tpu.wait(pending, num_returns=1,
                                       timeout=3600)
            pending = list(rest)
            # wait may surface several completions at once: drain them all
            # (dropping any would silently lose results).
            for ref in ready:
                for v in ray_tpu.get(ref):
                    yield v

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("join() before close()")
        if self._inflight:
            ray_tpu.wait(self._inflight,
                         num_returns=len(self._inflight), timeout=-1.0)
            self._inflight = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
