"""Device profiling: XLA/TPU traces through jax.profiler.

Role-equivalent to the reference's profiling hooks (reference:
python/ray/_private/profiling.py + the nsight runtime-env plugin at
_private/runtime_env/nsight.py for CUDA) — on TPU the profiler of record
is XLA's own (jax.profiler → TensorBoard/XProf: device timelines, HLO
cost analysis, MXU utilization), so this module wraps it with the
framework's conventions instead of shipping a vendor plugin:

    from ray_tpu.util import profiling

    with profiling.device_trace("/tmp/tb"):       # whole-section trace
        for step in range(10):
            with profiling.step_annotation(step): # XLA StepMarker
                state, _ = train_step(state, batch)

View with ``tensorboard --logdir /tmp/tb`` (the trace lands under
``plugins/profile``).  Works on CPU too (host tracing only), so tests and
dry runs exercise the same code path as TPU runs.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Iterator, Optional

logger = logging.getLogger(__name__)

#: jax.profiler is process-global: one capture at a time.  Guarded here so
#: a second ``device_trace`` fails TYPED instead of raising deep inside
#: start_trace and leaving the first capture wedged.
_active_lock = threading.Lock()
_active_dir: Optional[str] = None


class ProfilerBusyError(RuntimeError):
    """A device trace is already being captured in this process."""


def active_trace_dir() -> Optional[str]:
    """Log dir of the capture in flight, or None when idle."""
    return _active_dir


@contextlib.contextmanager
def device_trace(log_dir: str, *,
                 host_tracer_level: Optional[int] = None) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed block into ``log_dir``.

    Raises :class:`ProfilerBusyError` when a capture is already active in
    this process (the underlying profiler is a process-global singleton).
    A failing ``stop_trace`` is logged, never raised: it must not mask the
    block's real exception, and the active flag is cleared either way so
    the next capture isn't wedged behind a corpse.
    """
    global _active_dir
    import jax

    with _active_lock:
        if _active_dir is not None:
            raise ProfilerBusyError(
                f"device trace already capturing into {_active_dir!r}")
        _active_dir = log_dir
    kwargs = {}
    if host_tracer_level is not None:
        try:
            kwargs["profiler_options"] = jax.profiler.ProfileOptions(
                host_tracer_level=host_tracer_level
            )
        except (AttributeError, TypeError):
            pass  # older jax: default options
    try:
        jax.profiler.start_trace(log_dir, **kwargs)
        try:
            yield
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:
                logger.warning("jax.profiler.stop_trace failed for %s",
                               log_dir, exc_info=True)
    finally:
        with _active_lock:
            _active_dir = None


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """Mark one train step so XProf groups device ops per step
    (jax.profiler.StepTraceAnnotation)."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield


@contextlib.contextmanager
def annotation(name: str) -> Iterator[None]:
    """Named region in the host timeline (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
