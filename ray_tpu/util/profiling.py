"""Device profiling: XLA/TPU traces through jax.profiler.

Role-equivalent to the reference's profiling hooks (reference:
python/ray/_private/profiling.py + the nsight runtime-env plugin at
_private/runtime_env/nsight.py for CUDA) — on TPU the profiler of record
is XLA's own (jax.profiler → TensorBoard/XProf: device timelines, HLO
cost analysis, MXU utilization), so this module wraps it with the
framework's conventions instead of shipping a vendor plugin:

    from ray_tpu.util import profiling

    with profiling.device_trace("/tmp/tb"):       # whole-section trace
        for step in range(10):
            with profiling.step_annotation(step): # XLA StepMarker
                state, _ = train_step(state, batch)

View with ``tensorboard --logdir /tmp/tb`` (the trace lands under
``plugins/profile``).  Works on CPU too (host tracing only), so tests and
dry runs exercise the same code path as TPU runs.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def device_trace(log_dir: str, *,
                 host_tracer_level: Optional[int] = None) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed block into ``log_dir``."""
    import jax

    kwargs = {}
    if host_tracer_level is not None:
        try:
            kwargs["profiler_options"] = jax.profiler.ProfileOptions(
                host_tracer_level=host_tracer_level
            )
        except (AttributeError, TypeError):
            pass  # older jax: default options
    jax.profiler.start_trace(log_dir, **kwargs)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """Mark one train step so XProf groups device ops per step
    (jax.profiler.StepTraceAnnotation)."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield


@contextlib.contextmanager
def annotation(name: str) -> Iterator[None]:
    """Named region in the host timeline (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
