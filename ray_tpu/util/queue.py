"""Distributed queue: a bounded multi-producer/multi-consumer channel.

Role-equivalent to the reference's ray.util.queue.Queue (reference:
python/ray/util/queue.py — an actor-backed asyncio.Queue with
blocking/timeout put/get and nowait/batch variants).  The backing actor is
ASYNC, so a blocked put/get parks a coroutine, not a thread — thousands of
waiters cost nothing (the repo's async-actor semaphore machinery does the
rest).
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio

        self._q: "asyncio.Queue" = asyncio.Queue(maxsize)

    # NOTE: no actor-side timed `put`: asyncio.wait_for(self._q.put(...))
    # cancellation RACES a successful insert — the caller would see Full
    # with the item actually enqueued.  Clients probe with put_nowait.

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item) -> bool:
        import asyncio

        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def put_nowait_batch(self, items: List[Any]) -> int:
        import asyncio

        n = 0
        for item in items:
            try:
                self._q.put_nowait(item)
                n += 1
            except asyncio.QueueFull:
                break
        return n

    async def get_nowait(self):
        import asyncio

        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def get_nowait_batch(self, max_items: int) -> List[Any]:
        import asyncio

        out: List[Any] = []
        while len(out) < max_items:
            try:
                out.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    """Client handle; picklable, so producers/consumers anywhere in the
    cluster share one queue (reference: queue.py Queue)."""

    # Infinite blocking is re-armed in bounded actor-side waits: each wait
    # parks a coroutine that EXPIRES at the slice boundary, so a caller
    # that dies never leaves an immortal consumer coroutine behind to
    # swallow a later item.
    _BLOCK_SLICE_S = 300.0

    def __init__(self, maxsize: int = 0, *,
                 actor_options: Optional[dict] = None,
                 _actor=None, _maxsize=None):
        if _actor is not None:
            self._actor = _actor
            self._maxsize = _maxsize
            return
        opts = dict(actor_options or {})
        cls = _QueueActor.options(**opts) if opts else _QueueActor
        self._actor = cls.remote(maxsize)
        self._maxsize = maxsize

    # -- blocking ------------------------------------------------------------

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        # put is NOT idempotent, so BOTH blocking paths probe with
        # put_nowait instead of a timed actor-side put: an actor-side
        # asyncio.wait_for(self._q.put(item)) whose cancellation races a
        # successful insert would make the client raise Full with the
        # item actually enqueued (phantom insert), and retrying a
        # timed-out put could double-insert if the first landed late.
        if not block:
            return self.put_nowait(item)
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok = ray_tpu.get(self._actor.put_nowait.remote(item),
                             timeout=60)
            if ok:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full(f"queue full after {timeout}s")
            time.sleep(0.05)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            return self.get_nowait()
        remaining = timeout
        while True:
            slice_s = (self._BLOCK_SLICE_S if remaining is None
                       else min(remaining, self._BLOCK_SLICE_S))
            ok, item = ray_tpu.get(self._actor.get.remote(slice_s),
                                   timeout=slice_s + 30)
            if ok:
                return item
            if remaining is not None:
                remaining -= slice_s
                if remaining <= 0:
                    raise Empty(f"queue empty after {timeout}s")

    # -- nowait --------------------------------------------------------------

    def put_nowait(self, item) -> None:
        if not ray_tpu.get(self._actor.put_nowait.remote(item),
                           timeout=60):
            raise Full("queue full")

    def get_nowait(self) -> Any:
        ok, item = ray_tpu.get(self._actor.get_nowait.remote(), timeout=60)
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait_batch(self, items: List[Any]) -> None:
        n = ray_tpu.get(self._actor.put_nowait_batch.remote(list(items)),
                        timeout=60)
        if n < len(items):
            raise Full(f"queue accepted only {n}/{len(items)} items")

    def get_nowait_batch(self, max_items: int) -> List[Any]:
        return ray_tpu.get(
            self._actor.get_nowait_batch.remote(max_items), timeout=60)

    # -- introspection -------------------------------------------------------

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=60)

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        if self._maxsize is None:  # handle rebuilt before maxsize shipped
            self._maxsize = ray_tpu.get(self._actor.maxsize.remote(),
                                        timeout=60)
        return self._maxsize > 0 and self.qsize() >= self._maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)

    def __reduce__(self):
        return (_rebuild_queue, (self._actor, self._maxsize))


def _rebuild_queue(actor, maxsize=None):
    return Queue(_actor=actor, _maxsize=maxsize)
