"""ray_tpu: a TPU-native distributed compute framework.

Core: tasks, actors, objects, placement groups over a shared-memory object
store and a resource-aware scheduler (capability parity with the reference
Ray core — see SURVEY.md §2).  Libraries: ray_tpu.train / .data / .tune /
.rllib / .serve, all built TPU-first on jax/pjit/shard_map/Pallas.
"""

from .core.api import (
    ActorClass,
    ActorHandle,
    NodeAffinitySchedulingStrategy,
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_log,
    init,
    is_initialized,
    kill,
    list_named_actors,
    method,
    nodes,
    placement_group,
    put,
    remote,
    remove_placement_group,
    shutdown,
    stack_dump,
    task_events,
    timeline,
    wait,
)
from .core.context import get_runtime_context
from .core.object_ref import ObjectRef, ObjectRefGenerator
from . import exceptions

__version__ = "0.1.0"

_SUBPACKAGES = (
    "data", "train", "tune", "serve", "rllib", "workflow", "dag",
    "collective", "util", "job_submission", "cluster_utils",
)


def __getattr__(name):
    """Lazy subpackage access: `ray_tpu.tune`, `ray_tpu.serve`, ... import
    on first touch (heavy deps like jax stay unloaded until needed)."""
    if name in _SUBPACKAGES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get", "put",
    "wait",
    "cancel", "kill", "get_actor", "list_named_actors", "placement_group",
    "remove_placement_group", "PlacementGroup",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "cluster_resources", "available_resources", "nodes", "timeline",
    "task_events", "get_log", "stack_dump",
    "ObjectRef", "ObjectRefGenerator", "ActorClass", "ActorHandle",
    "exceptions", "get_runtime_context", "__version__",
]
