"""RT005: undaemonized threads without a join path.

A non-daemon thread with no ``join()`` keeps the interpreter alive after
``main`` returns — in a worker that's a hung process the head must
health-check-reap; in the driver it's a script that never exits.  Either
mark the thread ``daemon=True`` (it holds no state that must flush) or
keep a reachable join path (then the non-daemon flag is the point:
exit waits for the flush).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import assigned_target, call_name, parent_map
from .rtlint import Finding, Project

THREAD_CALLS = {"threading.Thread", "Thread"}


def _module_join_info(tree) -> Tuple[Set[str], Dict[str, str]]:
    """(terminal names `.join()`/`.daemon = True` is applied to,
    alias map  alias_terminal -> source_terminal from `t = self.x`)."""
    handled: Set[str] = set()
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            name = call_name(node)
            if name:
                parts = name.split(".")
                if len(parts) >= 2:
                    handled.add(parts[-2])
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t, v = node.targets[0], node.value
            # x.daemon = True
            if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                    and isinstance(v, ast.Constant) and v.value is True:
                base = t.value
                term = (base.attr if isinstance(base, ast.Attribute)
                        else base.id if isinstance(base, ast.Name) else None)
                if term:
                    handled.add(term)
            # t = self._pending  (alias for a later t.join())
            elif isinstance(t, ast.Name) and isinstance(
                    v, (ast.Name, ast.Attribute)):
                src = (v.attr if isinstance(v, ast.Attribute) else v.id)
                aliases[t.id] = src
    return handled, aliases


def _daemon_kw(call: ast.Call) -> Optional[bool]:
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True  # dynamic daemon= — assume deliberate
    return None


def check_rt005(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for module in project.modules:
        parents = parent_map(module.tree)
        handled, aliases = _module_join_info(module.tree)
        # Resolve one level of aliasing: `t = self._x; t.join()` covers _x.
        joined = set(handled)
        for alias in handled:
            if alias in aliases:
                joined.add(aliases[alias])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or call_name(node) not in THREAD_CALLS:
                continue
            if _daemon_kw(node):
                continue
            # daemon missing (or explicitly False): require a join path.
            target = assigned_target(node, parents)
            if target is not None and target in joined:
                continue
            out.append(Finding(
                    "RT005", module.rel, node.lineno,
                    "threading.Thread without daemon=True and no visible "
                    "join path — a leaked non-daemon thread hangs "
                    "interpreter exit; set daemon=True or join it",
                ))
    return out
