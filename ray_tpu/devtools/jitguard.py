"""Runtime recompile sentinel: a trace-count registry for jitted programs.

The serving/training perf story rests on ONE compiled program per hot
path: the engine asserts ``decode_traces == 1`` after warmup, and the
flight recorder attributes any step that paid a compile.  Those counters
used to live in ``models/paged.py``; this module generalizes them into a
registry ANY module can join (the paged programs, the rllib learner
updates, future kernels) and adds the dynamic twin of rtlint RT010 —
sibling of :mod:`devtools.locks` (RT_DEBUG_LOCKS):

- **disabled** (default): :func:`bump` is a plain counter increment at
  trace time — exactly the old ``models.paged._bump`` behavior, zero
  added work on any jitted call (python bodies only run while tracing).
- **enabled** (``RT_DEBUG_JIT=1``): after :func:`arm` (the engine calls
  it at the end of ``warmup()``; tests/bench can call it directly), any
  growth in an armed program's trace count raises
  :class:`RecompileError` naming the program, the argument
  treeshape/dtype delta versus the last trace, and the call site that
  triggered the recompile — the steady-state loop fails loudly at the
  FIRST stray specialization instead of silently paying a compile per
  step.

Programs join by bumping inside their jitted body::

    @jax.jit
    def step(xs):
        jitguard.bump("step", jitguard.signature_of({"xs": xs}))
        ...

``models.paged`` keeps its old ``trace_count``/``trace_counts`` names as
aliases over this registry, so ``devmem`` snapshots and the engine's
``decode_traces`` assertions are unchanged.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, Optional

ENV_FLAG = "RT_DEBUG_JIT"


class RecompileError(RuntimeError):
    """An armed program re-traced after warmup — some argument's
    treeshape/dtype/static value drifted and XLA compiled a new
    specialization on the hot path."""


def enabled() -> bool:
    """Sentinel armed-on-arm()?  Off means :func:`arm` is a no-op and
    :func:`bump` stays the identity counter path."""
    return os.environ.get(ENV_FLAG, "") in ("1", "true", "yes")


# Registry state.  Locked: learner updates and the engine loop may trace
# on different threads.  Bumps happen only at TRACE time, never per step.
_lock = threading.Lock()
_counts: Dict[str, int] = {}
_sigs: Dict[str, Any] = {}          # program -> last traced signature
_baseline: Dict[str, int] = {}      # armed program -> count at arm()


def reset_sentinel_state() -> None:
    """Forget every count, signature, and armed baseline (tests)."""
    with _lock:
        _counts.clear()
        _sigs.clear()
        _baseline.clear()


def register_program(name: str) -> None:
    """Declare a program.  Registration before the first trace makes it
    visible in :func:`counts` snapshots at 0.  Re-registering an ARMED
    program stands its baseline down until the next :func:`arm` —
    building a new component that shares the program (a fresh engine,
    adapter pool, or learner) opens a legitimate compile phase, not a
    hot-path recompile."""
    with _lock:
        _counts.setdefault(name, 0)
        _baseline.pop(name, None)


def count(name: str) -> int:
    """Times the named program was traced (compiled)."""
    return _counts.get(name, 0)


def counts() -> Dict[str, int]:
    """Snapshot of every registered program's trace count."""
    with _lock:
        return dict(_counts)


def signature_of(arrays: Dict[str, Any]) -> Dict[str, Any]:
    """Treeshape/dtype signature of named (tracer or concrete) arrays —
    what the jitted body passes to :func:`bump` so a post-warmup
    recompile can say WHICH argument drifted."""
    out: Dict[str, Any] = {}
    for k, v in arrays.items():
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None:
            out[k] = (tuple(shape), str(dtype))
        else:
            out[k] = f"{type(v).__name__}:{v!r}"[:80]
    return out


def _delta(old: Optional[Dict[str, Any]],
           new: Optional[Dict[str, Any]]) -> str:
    if not isinstance(old, dict) or not isinstance(new, dict):
        return f"prev={old!r} now={new!r}"
    parts = []
    for k in sorted(set(old) | set(new)):
        a, b = old.get(k), new.get(k)
        if a != b:
            parts.append(f"{k}: {a!r} -> {b!r}")
    return "; ".join(parts) if parts else "identical visible signature " \
        "(a static arg or closure constant changed)"


def _call_site() -> str:
    """The deepest non-jax, non-jitguard project frame below us — the
    call that triggered this trace (the traced body's own frame is the
    one directly above bump; its CALLER past the jax machinery is what
    an operator can go fix)."""
    frames = [f for f in traceback.extract_stack()
              if f.filename != __file__
              and "/jax/" not in f.filename.replace("\\", "/")
              and "jax/_src" not in f.filename]
    # frames[-1] is the traced body; the next project frame up is the
    # call site.  A direct call (tests) leaves only the body.
    if len(frames) >= 2:
        f = frames[-2]
    elif frames:
        f = frames[-1]
    else:
        return "<unknown>"
    return f"{f.filename}:{f.lineno} in {f.name}"


def bump(name: str, signature: Optional[Dict[str, Any]] = None) -> None:
    """Record one trace of ``name``.  Called INSIDE jitted bodies (python
    executes only while tracing, so a bump == a compile).  When the
    sentinel is armed and this program's baseline is exceeded, raise
    :class:`RecompileError` with the signature delta and call site."""
    with _lock:
        n = _counts.get(name, 0) + 1
        _counts[name] = n
        prev_sig = _sigs.get(name)
        if signature is not None:
            _sigs[name] = signature
        baseline = _baseline.get(name)
    if baseline is not None and n > baseline:
        raise RecompileError(
            f"program {name!r} recompiled after warmup (trace "
            f"{n} > armed baseline {baseline}): arg delta "
            f"[{_delta(prev_sig, signature)}] — triggered at "
            f"{_call_site()}"
        )


def arm(force: bool = False) -> bool:
    """Freeze every currently-registered program's trace count as its
    baseline.  No-op (returns False) unless ``RT_DEBUG_JIT=1`` or
    ``force`` — the disabled path stays the identity counter.  Programs
    registered AFTER arming are unarmed until the next :func:`arm` (a
    late-joining learner must get its own warmup trace)."""
    if not (enabled() or force):
        return False
    with _lock:
        _baseline.clear()
        _baseline.update(_counts)
    return True


def disarm() -> None:
    with _lock:
        _baseline.clear()


def armed() -> bool:
    return bool(_baseline)
