"""RT001/RT002: the event-loop safety rules.

The head is ONE asyncio loop owning all control-plane state ("handlers
never block" — core/head.py's contract).  A single synchronous
``time.sleep``/socket read/RPC round-trip inside an ``async def`` stalls
every connected client; a ``threading`` lock held across an ``await``
can deadlock against the executor threads that legitimately take it.
"""

from __future__ import annotations

import ast
from typing import List

from .astutil import (call_name, contains_await, dotted_name, is_awaited,
                      iter_functions, parent_map, walk_own_body)
from .rtlint import Finding, Project

#: exact dotted calls that block the calling thread.
BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.waitpid",
    "os.replace",
    "socket.create_connection",
    "shutil.rmtree",
    "glob.glob",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.getoutput",
}
#: method names that block regardless of receiver (sockets, pipes, procs).
BLOCKING_METHODS = {
    "recv", "recv_into", "recvfrom", "sendall", "accept", "communicate",
}
#: file-read methods — flagged only when NOT awaited (``await reader.read``
#: on an asyncio stream is the non-blocking form).
FILE_METHODS = {"read", "readline", "readlines"}
#: receivers whose synchronous ``.call(...)`` is a blocking RPC round-trip
#: (RpcClient.call parks the calling thread on a concurrent future).
SYNC_RPC_RECEIVERS = {"rpc", "head", "client", "cl"}


def _async_calls(module):
    parents = parent_map(module.tree)
    for fn in iter_functions(module.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in walk_own_body(fn):
            if isinstance(node, ast.Call):
                yield fn, node, parents


def check_rt001(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for module in project.modules:
        for fn, call, parents in _async_calls(module):
            if is_awaited(call, parents):
                continue
            name = call_name(call)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            msg = None
            if name in BLOCKING_CALLS or name.startswith("subprocess."):
                msg = f"blocking {name}()"
            elif name == "open":
                msg = "blocking open() (file I/O)"
            elif "." in name and last in BLOCKING_METHODS:
                msg = f"blocking .{last}() on {name.rsplit('.', 1)[0]}"
            elif "." in name and last in FILE_METHODS \
                    and isinstance(call.func.value, ast.Name):
                msg = f"blocking .{last}() on {name.rsplit('.', 1)[0]}"
            elif last == "call" and "." in name:
                receiver = name.rsplit(".", 1)[0].rsplit(".", 1)[-1]
                if receiver in SYNC_RPC_RECEIVERS:
                    msg = f"synchronous RPC {name}()"
            if msg:
                out.append(Finding(
                    "RT001", module.rel, call.lineno,
                    f"{msg} inside async def {fn.name} stalls the event "
                    "loop — move it to run_in_executor or an async API",
                ))
    return out


def _lockish(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last or "mutex" in last


def check_rt002(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for module in project.modules:
        for fn in iter_functions(module.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_own_body(fn):
                if not isinstance(node, ast.With):
                    continue
                held = [
                    dotted_name(item.context_expr)
                    for item in node.items
                    if _lockish(item.context_expr)
                ]
                if held and contains_await(node):
                    out.append(Finding(
                        "RT002", module.rel, node.lineno,
                        f"lock {held[0]} held across an await in async def "
                        f"{fn.name} — the loop parks while every thread "
                        "contending the lock deadlocks behind it; shrink "
                        "the critical section to exclude the await",
                    ))
    return out
