"""RT012: deadline/backoff contract drift.

``core/deadline.py`` is THE retry shape: every retry/reconnect loop
backs off on one jittered curve (:class:`BackoffPolicy`) and bounds
itself with a monotonic budget (:class:`Deadline`).  Drift away from it
re-introduces exactly the pathologies the module was built to kill —
synchronized redial storms after a head restart, loops that never give
up, and "infinite" sentinel timeouts that turn a hung peer into a hung
caller.

Findings:

- **hand-rolled retry curve** — ``time.sleep(expr)`` inside a loop
  where the delay is computed from the attempt counter (the loop
  variable or an ``x += 1``-style counter) in a function that never
  touches a ``BackoffPolicy``.  The curve exists; use it — it caps,
  jitters, and clips to the deadline.
- **unbounded re-dial loop** — ``while True`` + ``except: sleep``
  where the handler neither re-raises nor breaks and the function has
  no Deadline/budget reference: a permanently-down peer spins this loop
  forever.
- **sentinel timeout** — ``timeout=<huge constant>`` (>= 1e6 s)
  smuggled through an API that accepts ``None`` for "no timeout": the
  constant lies to every reader and survives unit conversions wrong.

A legitimately-infinite wait (a stream read paced by its producer) is
vetted with a trailing ``# rt-deadline-ok: <reason>``.

``--json`` meta names the loop site and the missing primitive so the
fix is mechanical.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .astutil import (call_name, dotted_name, iter_functions, parent_map,
                      walk_own_body, _line_annotation)
from .rtlint import Finding, Project

_DEADLINE_OK_RE = re.compile(r"#\s*rt-deadline-ok:\s*(.+?)\s*$")

_LOOPS = (ast.For, ast.While, ast.AsyncFor)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: names whose presence marks the function as on-contract.
_POLICY_MARKS = frozenset({"BackoffPolicy", "call_policy",
                           "reconnect_policy", "backoff"})
_DEADLINE_MARKS = frozenset({"Deadline", "deadline", "expired",
                             "remaining", "budget"})

_SENTINEL_S = 1e6  # anything "longer than a CI run" is a lie, not a bound


def _marks(fn: ast.AST) -> Set[str]:
    """Identifier tails referenced anywhere in the function body (nested
    defs included: retry helpers close over the policy)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _has_policy(fn: ast.AST) -> bool:
    m = _marks(fn)
    return bool(m & _POLICY_MARKS) \
        or any("policy" in name.lower() for name in m)


def _has_deadline(fn: ast.AST) -> bool:
    m = _marks(fn)
    if m & _DEADLINE_MARKS:
        return True
    return any("deadline" in name.lower() for name in m)


def _aug_counters(fn: ast.AST) -> Set[str]:
    """Names stepped with ``x += ...`` (attempt counters)."""
    return {node.target.id for node in walk_own_body(fn)
            if isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Name)}


def _loop_vars(loop: ast.AST) -> Set[str]:
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        return {n.id for n in ast.walk(loop.target)
                if isinstance(n, ast.Name)}
    return set()


def _enclosing_loop(node: ast.AST, pmap, fn) -> Optional[ast.AST]:
    cur = pmap.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, _LOOPS):
            return cur
        if isinstance(cur, _FUNC_NODES):
            return None
        cur = pmap.get(cur)
    return None


def _is_while_true(loop: ast.AST) -> bool:
    return isinstance(loop, ast.While) \
        and isinstance(loop.test, ast.Constant) \
        and bool(loop.test.value)


def check_rt012(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        pmap = parent_map(mod.tree)
        for fn in iter_functions(mod.tree):
            # Only top-level walk per function: nested defs get their own
            # iteration.
            has_policy = None  # lazy: _marks walks the whole body
            has_deadline = None
            counters = None
            for node in walk_own_body(fn):
                if isinstance(node, ast.Call) \
                        and call_name(node) == "time.sleep" and node.args:
                    loop = _enclosing_loop(node, pmap, fn)
                    if loop is None:
                        continue
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant):
                        continue  # constant-interval poll, not a curve
                    if counters is None:
                        counters = _aug_counters(fn)
                    curve_names = _loop_vars(loop) | counters
                    refs = {n.id for n in ast.walk(arg)
                            if isinstance(n, ast.Name)}
                    if not refs & curve_names:
                        continue
                    if has_policy is None:
                        has_policy = _has_policy(fn)
                    if has_policy:
                        continue
                    if _line_annotation(mod, node.lineno, _DEADLINE_OK_RE):
                        continue
                    out.append(Finding(
                        "RT012", mod.rel, node.lineno,
                        f"hand-rolled retry curve in {fn.name!r}: "
                        "time.sleep() computed from the attempt counter "
                        "instead of core.deadline.BackoffPolicy — the "
                        "shared curve caps, jitters, and clips to the "
                        "caller's Deadline",
                        meta={"kind": "retry_curve",
                              "loop_line": loop.lineno,
                              "missing": "BackoffPolicy"}))
                elif isinstance(node, ast.Try):
                    f = _check_redial(mod, fn, pmap, node)
                    if f is not None:
                        if has_deadline is None:
                            has_deadline = _has_deadline(fn)
                        if not has_deadline:
                            out.append(f)
                elif isinstance(node, ast.Call):
                    out.extend(_check_sentinel(mod, node))
    return sorted(out, key=lambda f: (f.path, f.line))


def _check_redial(mod, fn, pmap, trynode: ast.Try) -> Optional[Finding]:
    """``while True`` wrapping try/except whose handler sleeps and never
    exits the loop: an unbounded re-dial."""
    loop = _enclosing_loop(trynode, pmap, fn)
    if loop is None or not _is_while_true(loop):
        return None
    for handler in trynode.handlers:
        sleeps = [n for n in ast.walk(ast.Module(body=handler.body,
                                                 type_ignores=[]))
                  if isinstance(n, ast.Call)
                  and call_name(n) == "time.sleep"]
        if not sleeps:
            continue
        exits = any(isinstance(n, (ast.Raise, ast.Return, ast.Break))
                    for s in handler.body for n in ast.walk(s))
        if exits:
            continue
        if _line_annotation(mod, sleeps[0].lineno, _DEADLINE_OK_RE):
            continue
        return Finding(
            "RT012", mod.rel, sleeps[0].lineno,
            f"unbounded re-dial loop in {fn.name!r}: while True + "
            "swallow-and-sleep with no Deadline — a permanently-down "
            "peer spins this forever; bound it with "
            "core.deadline.Deadline (raise when .expired)",
            meta={"kind": "unbounded_redial", "loop_line": loop.lineno,
                  "missing": "Deadline"})
    return None


def _check_sentinel(mod, call: ast.Call) -> List[Finding]:
    out: List[Finding] = []
    for kw in call.keywords:
        if kw.arg is None or not kw.arg.startswith("timeout"):
            continue
        huge = _huge_const(kw.value)
        if huge is None:
            continue
        if _line_annotation(mod, kw.value.lineno, _DEADLINE_OK_RE):
            continue
        out.append(Finding(
            "RT012", mod.rel, kw.value.lineno,
            f"sentinel timeout {kw.arg}={huge:g}: an 'infinite' constant "
            "masquerading as a bound — pass timeout=None (and plumb "
            "Optional[float]) when the wait is genuinely unbounded, or a "
            "real Deadline-derived budget when it is not",
            meta={"kind": "sentinel_timeout", "value": huge,
                  "keyword": kw.arg}))
    return out


def _huge_const(node: ast.AST) -> Optional[float]:
    """A numeric constant >= the sentinel threshold anywhere in the
    timeout expression (covers ``1e9 if x < 0 else x + 30``)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) \
                and isinstance(n.value, (int, float)) \
                and not isinstance(n.value, bool) \
                and float(n.value) >= _SENTINEL_S:
            return float(n.value)
    return None
