"""Shared AST plumbing for the rtlint rules.

Everything here is dependency-free stdlib ``ast`` work: rules must stay
importable (and runnable over a scratch tree) without initializing any of
the framework's runtime machinery.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (calls, subscripts
    and other dynamic receivers don't have a static dotted form)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


def iter_functions(tree: ast.AST) -> Iterator[ast.AST]:
    """Every function/async-function definition in the module."""
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            yield node


def walk_own_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body EXCLUDING nested function/class definitions:
    a nested ``def`` has its own execution context (it may run in an
    executor, a thread, or never), so its statements are not attributable
    to the enclosing function's thread/loop."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def contains_await(node: ast.AST) -> bool:
    """True if the node's own body (nested defs excluded) awaits."""
    return any(isinstance(n, ast.Await) for n in walk_own_body(node))


def is_awaited(call: ast.Call, parents: Dict[ast.AST, ast.AST]) -> bool:
    return isinstance(parents.get(call), ast.Await)


def decorator_names(node: ast.AST) -> List[str]:
    """Dotted names of a def/class's decorators; ``@d(...)`` reports the
    callee ``d``."""
    out: List[str] = []
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name:
            out.append(name)
    return out


def assigned_target(call: ast.Call,
                    parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
    """Terminal name a call's result is bound to (``x = f()`` -> ``x``,
    ``self.x = f()`` -> ``x``), else None."""
    parent = parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
    return None


def str_dict_literal(tree: ast.AST, var: str) -> Optional[Dict[str, str]]:
    """Parse a module-level ``var = {"k": "v", ...}`` assignment without
    importing the module."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == var for t in targets):
            if not isinstance(node.value, ast.Dict):
                return None
            out: Dict[str, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks, vs = const_str(k), const_str(v)
                if ks is not None and vs is not None:
                    out[ks] = vs
            return out
    return None


def str_collection_literal(tree: ast.AST, var: str) -> Optional[List[str]]:
    """String constants inside a module-level ``var = frozenset({...})`` /
    set / tuple / list / dict-keys assignment, without importing."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == var for t in node.targets
        ):
            return [
                n.value for n in ast.walk(node.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            ]
    return None


def enclosing_functions(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> List[ast.AST]:
    """Function defs lexically enclosing ``node``, innermost first."""
    out: List[ast.AST] = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, _FUNC_NODES):
            out.append(cur)
        cur = parents.get(cur)
    return out


def local_names(fn: ast.AST) -> set:
    """Parameter + locally-bound names of a function (its own body only)."""
    args = fn.args
    names = {
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
    }
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in walk_own_body(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    # Direct child defs/classes bind their names in this scope too.
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, _FUNC_NODES + (ast.ClassDef,)):
            names.add(child.name)
    return names


def module_scope_names(tree: ast.AST) -> set:
    """Names bound at MODULE scope only — nested function/class bodies are
    excluded (their Store names are locals, and treating them as module
    globals would mask closure captures)."""
    names = set()
    for node in walk_own_body(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    for child in ast.iter_child_nodes(tree):
        if isinstance(child, _FUNC_NODES + (ast.ClassDef,)):
            names.add(child.name)
    return names
