"""Shared AST plumbing for the rtlint rules.

Everything here is dependency-free stdlib ``ast`` work: rules must stay
importable (and runnable over a scratch tree) without initializing any of
the framework's runtime machinery.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (calls, subscripts
    and other dynamic receivers don't have a static dotted form)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


def iter_functions(tree: ast.AST) -> Iterator[ast.AST]:
    """Every function/async-function definition in the module."""
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            yield node


def walk_own_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body EXCLUDING nested function/class definitions:
    a nested ``def`` has its own execution context (it may run in an
    executor, a thread, or never), so its statements are not attributable
    to the enclosing function's thread/loop."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def contains_await(node: ast.AST) -> bool:
    """True if the node's own body (nested defs excluded) awaits."""
    return any(isinstance(n, ast.Await) for n in walk_own_body(node))


def is_awaited(call: ast.Call, parents: Dict[ast.AST, ast.AST]) -> bool:
    return isinstance(parents.get(call), ast.Await)


def decorator_names(node: ast.AST) -> List[str]:
    """Dotted names of a def/class's decorators; ``@d(...)`` reports the
    callee ``d``."""
    out: List[str] = []
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name:
            out.append(name)
    return out


def assigned_target(call: ast.Call,
                    parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
    """Terminal name a call's result is bound to (``x = f()`` -> ``x``,
    ``self.x = f()`` -> ``x``), else None."""
    parent = parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
    return None


def str_dict_literal(tree: ast.AST, var: str) -> Optional[Dict[str, str]]:
    """Parse a module-level ``var = {"k": "v", ...}`` assignment without
    importing the module."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == var for t in targets):
            if not isinstance(node.value, ast.Dict):
                return None
            out: Dict[str, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks, vs = const_str(k), const_str(v)
                if ks is not None and vs is not None:
                    out[ks] = vs
            return out
    return None


def str_collection_literal(tree: ast.AST, var: str) -> Optional[List[str]]:
    """String constants inside a module-level ``var = frozenset({...})`` /
    set / tuple / list / dict-keys assignment, without importing."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == var for t in node.targets
        ):
            return [
                n.value for n in ast.walk(node.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            ]
    return None


def enclosing_functions(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> List[ast.AST]:
    """Function defs lexically enclosing ``node``, innermost first."""
    out: List[ast.AST] = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, _FUNC_NODES):
            out.append(cur)
        cur = parents.get(cur)
    return out


def local_names(fn: ast.AST) -> set:
    """Parameter + locally-bound names of a function (its own body only)."""
    args = fn.args
    names = {
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
    }
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in walk_own_body(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    # Direct child defs/classes bind their names in this scope too.
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, _FUNC_NODES + (ast.ClassDef,)):
            names.add(child.name)
    return names


def module_scope_names(tree: ast.AST) -> set:
    """Names bound at MODULE scope only — nested function/class bodies are
    excluded (their Store names are locals, and treating them as module
    globals would mask closure captures)."""
    names = set()
    for node in walk_own_body(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    for child in ast.iter_child_nodes(tree):
        if isinstance(child, _FUNC_NODES + (ast.ClassDef,)):
            names.add(child.name)
    return names


# ==============================================================================
# Concurrency model: call graph, thread-role inference, guard inference.
#
# The dataplane split (PR 6) means core/ state is mutated concurrently from
# the asyncio loops, the shared peer-loop thread, per-connection reader
# threads, executors, and throwaway offload threads.  The model below is the
# shared substrate for rules RT007 (guarded-by races) and RT008 (static
# lock-order cycles): pure ``ast`` work, nothing imported or executed.
#
# Thread roles (a role = one CLASS of threads; two accesses race only when
# their role sets differ):
#
#   main       entry from user/API threads (functions nothing in the
#              analyzed tree is seen to call)
#   loop       an asyncio event-loop thread: every ``async def``, plus sync
#              callbacks a loop runs (``call_soon``/``call_soon_threadsafe``
#              targets, ``on_push``/``subscribe`` handlers, future
#              ``add_done_callback``s — resolved by RPC read loops)
#   executor   ``run_in_executor`` / ``ThreadPoolExecutor.submit`` targets
#   thread:N   dedicated ``threading.Thread(target=..., name="N")`` targets
#   gc         ``__del__`` (cyclic GC runs it on whatever thread allocates)
#
# Known approximation: all event loops in one process collapse into one
# ``loop`` role, so a race strictly between two DIFFERENT loops (head loop
# vs peer loop) with no other role touching the field is not reported.
# Every real core/ field that multiple loops touch is also touched from
# ``main``, which does get reported.
#
# Annotations (documented in CONTRIBUTING.md):
#   # rt-role: <role>           on a def/lambda line — asserts the function
#                               runs under that role (escaping callbacks)
#   # rt-unguarded: <reason>    on an attribute-access line — vets that
#                               (class, attr) as an intentional unguarded
#                               cross-thread handoff
#   _RT_UNGUARDED = {"attr": "reason", ...}     class-level bulk form
#   _RT_GUARDED_BY = {"attr": "_lock_attr", ...}  declared guard map; RT007
#                               verifies it statically and devtools.locks
#                               enforces it at runtime under RT_DEBUG_LOCKS=2
# ==============================================================================

ROLE_MAIN = "main"
ROLE_LOOP = "loop"
ROLE_EXECUTOR = "executor"
ROLE_GC = "gc"

#: receiver-method call sites (``something.m()``) resolve cross-class only
#: when ``m`` is defined by exactly ONE class in the analyzed tree and is
#: not one of these ubiquitous names (dict/list/socket/file/future verbs
#: would resolve half the stdlib onto project classes).
_COMMON_METHODS = frozenset({
    "get", "put", "set", "pop", "add", "close", "run", "start", "stop",
    "call", "send", "recv", "submit", "wait", "cancel", "append", "remove",
    "clear", "update", "items", "keys", "values", "result", "done", "join",
    "acquire", "release", "flush", "write", "read", "register", "connect",
    "main", "handler", "shutdown", "exception", "copy", "sort", "extend",
    "insert", "discard", "setdefault", "split", "strip", "encode", "decode",
    "format", "create", "exists", "name", "free", "notify", "count",
})

#: container methods that mutate their receiver — ``self._x.append(...)``
#: is a write-shaped access to ``_x`` even though the attr node loads.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "move_to_end", "extendleft",
})

#: constructors whose instances are internally synchronized: accesses
#: through them (``self._q.put(...)``) are not races.
_THREADSAFE_CTORS = frozenset({
    "threading.Event", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "ThreadPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
})

#: lock factories.  kind "thread" locks participate in RT008 ordering;
#: asyncio locks serialize tasks on one loop, not threads, so they guard
#: (RT007) but impose no cross-thread order.
_LOCK_CTORS = {
    "make_lock": "thread", "make_rlock": "thread",
    "locks.make_lock": "thread", "locks.make_rlock": "thread",
    "threading.Lock": "thread", "threading.RLock": "thread",
    "asyncio.Lock": "async",
}

_ROLE_RE = re.compile(r"#\s*rt-role:\s*([A-Za-z0-9:_\-]+)")
_UNGUARDED_RE = re.compile(r"#\s*rt-unguarded:\s*(.+?)\s*$")


class FuncInfo:
    """One function/lambda in the analyzed tree."""

    __slots__ = ("node", "module", "cls", "name", "qualname", "parent",
                 "children", "is_async", "roles", "role_seeds", "entry_held",
                 "has_caller", "lineno", "def_site_held")

    def __init__(self, node, module, cls, name, qualname, parent):
        self.node = node
        self.module = module          # Module (rtlint)
        self.cls = cls                # innermost enclosing class name or None
        self.name = name
        self.qualname = qualname
        self.parent = parent          # enclosing FuncInfo or None
        self.children: Dict[str, "FuncInfo"] = {}
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.roles: Set[str] = set()
        self.role_seeds: Set[str] = set()
        self.entry_held: Optional[FrozenSet[str]] = None  # None = unknown/top
        self.has_caller = False       # some resolved call site targets it
        self.lineno = getattr(node, "lineno", 0)
        # Locks lexically held where a NESTED def/lambda appears: a nested
        # orphan (sorted keys, local helpers) runs right there, so it
        # inherits these along with the parent's entry set.
        self.def_site_held: FrozenSet[str] = frozenset()

    def __repr__(self):
        return f"<FuncInfo {self.module.rel}:{self.qualname}>"


class Access:
    """One ``self.<attr>`` access inside a method body."""

    __slots__ = ("cls_key", "attr", "kind", "func", "line", "held")

    def __init__(self, cls_key, attr, kind, func, line, held):
        self.cls_key = cls_key  # (module_rel, class_name)
        self.attr = attr
        self.kind = kind        # "write" | "read"
        self.func = func        # FuncInfo
        self.line = line
        self.held = held        # FrozenSet[str] lexically held lock ids

    def effective_held(self) -> FrozenSet[str]:
        extra = self.func.entry_held or frozenset()
        return self.held | extra


class ClassInfo:
    __slots__ = ("module", "name", "node", "lock_attrs", "lock_kinds",
                 "guarded_by", "unguarded", "threadsafe_attrs", "lineno",
                 "attr_types")

    def __init__(self, module, name, node):
        self.module = module
        self.name = name
        self.node = node
        self.lock_attrs: Dict[str, str] = {}   # attr -> canonical lock id
        self.lock_kinds: Dict[str, str] = {}   # attr -> "thread"|"async"
        self.guarded_by: Dict[str, str] = {}   # declared _RT_GUARDED_BY
        self.unguarded: Dict[str, str] = {}    # declared _RT_UNGUARDED
        self.threadsafe_attrs: Set[str] = set()
        self.lineno = node.lineno
        # self.X = ProjectClass(...) — light type inference so calls
        # through the attribute (self.scheduler.acquire(...)) resolve.
        self.attr_types: Dict[str, Tuple[str, str]] = {}

    @property
    def key(self):
        return (self.module.rel, self.name)


class Acquisition:
    """One ``with <lock>:`` acquisition site."""

    __slots__ = ("lock", "kind", "func", "line", "held")

    def __init__(self, lock, kind, func, line, held):
        self.lock = lock
        self.kind = kind
        self.func = func
        self.line = line
        self.held = held  # frozenset held lexically just before this acquire


class CallSite:
    __slots__ = ("callee", "func", "line", "held")

    def __init__(self, callee, func, line, held):
        self.callee = callee  # FuncInfo
        self.func = func      # caller FuncInfo
        self.line = line
        self.held = held


def _line_annotation(module, lineno, regex) -> Optional[str]:
    try:
        line = module.source.splitlines()[lineno - 1]
    except IndexError:
        return None
    m = regex.search(line)
    return m.group(1) if m else None


class ConcurrencyModel:
    """Interprocedural view of a set of modules: who runs what (thread
    roles), which lock guards what (guard maps), and which locks nest
    inside which (ordering edges)."""

    def __init__(self, modules: List):
        self.modules = list(modules)
        self.functions: List[FuncInfo] = []
        self._by_node: Dict[int, FuncInfo] = {}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self._methods: Dict[Tuple[str, str, str], FuncInfo] = {}
        self._module_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        self._module_locks: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.accesses: List[Access] = []
        self.acquisitions: List[Acquisition] = []
        self.call_sites: List[CallSite] = []
        self._unique_methods: Dict[str, FuncInfo] = {}
        self._build_catalog()
        self._build_class_info()
        self._index_unique_methods()
        self._extract_bodies()
        self._propagate_roles()
        self._solve_entry_held()
        # Re-derive effective held sets now that entry_held is known: the
        # Access objects keep lexical held; effective_held() adds entry.

    # -- discovery -------------------------------------------------------------

    def _build_catalog(self):
        for mod in self.modules:
            self._scan_scope(mod, mod.tree, None, None)

    def _scan_scope(self, mod, node, cls, parent_func):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._scan_scope(mod, child, child.name, None)
            elif isinstance(child, _FUNC_NODES + (ast.Lambda,)):
                name = getattr(child, "name",
                               f"<lambda:{child.lineno}>")
                qual = (f"{parent_func.qualname}.{name}" if parent_func
                        else f"{cls}.{name}" if cls else name)
                info = FuncInfo(child, mod, cls, name, qual, parent_func)
                self.functions.append(info)
                self._by_node[id(child)] = info
                if parent_func is not None:
                    parent_func.children[name] = info
                elif cls is not None:
                    self._methods[(mod.rel, cls, name)] = info
                else:
                    self._module_funcs[(mod.rel, name)] = info
                # Intrinsic role seeds.
                if info.is_async:
                    info.role_seeds.add(ROLE_LOOP)
                if name == "__del__":
                    info.role_seeds.add(ROLE_GC)
                explicit = _line_annotation(mod, child.lineno, _ROLE_RE)
                if explicit:
                    info.role_seeds.add(explicit)
                self._scan_scope(mod, child, cls, info)
            else:
                self._scan_scope(mod, child, cls, parent_func)

    def _build_class_info(self):
        for mod in self.modules:
            # Module-level locks: X = make_lock("name") / threading.Lock().
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Call):
                    ctor = dotted_name(stmt.value.func)
                    if ctor in _LOCK_CTORS:
                        var = stmt.targets[0].id
                        lock_id = self._lock_name(stmt.value, mod, None, var)
                        self._module_locks[(mod.rel, var)] = (
                            lock_id, _LOCK_CTORS[ctor])
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                ci = ClassInfo(mod, node.name, node)
                self.classes[ci.key] = ci
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name):
                        var = stmt.targets[0].id
                        if var in ("_RT_GUARDED_BY", "_RT_UNGUARDED") \
                                and isinstance(stmt.value, ast.Dict):
                            out = {}
                            for k, v in zip(stmt.value.keys,
                                            stmt.value.values):
                                ks, vs = const_str(k), const_str(v)
                                if ks is not None and vs is not None:
                                    out[ks] = vs
                            if var == "_RT_GUARDED_BY":
                                ci.guarded_by = out
                            else:
                                ci.unguarded = out
                # self.<attr> = <lock ctor>() / <threadsafe ctor>() anywhere
                # in the class body (constructors usually, but not only).
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign) \
                            or len(sub.targets) != 1:
                        continue
                    t = sub.targets[0]
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    if not isinstance(sub.value, ast.Call):
                        continue
                    ctor = dotted_name(sub.value.func)
                    if ctor in _LOCK_CTORS:
                        ci.lock_attrs[t.attr] = self._lock_name(
                            sub.value, mod, node.name, t.attr)
                        ci.lock_kinds[t.attr] = _LOCK_CTORS[ctor]
                    elif ctor in _THREADSAFE_CTORS:
                        ci.threadsafe_attrs.add(t.attr)
                    elif ctor is not None:
                        ci.attr_types[t.attr] = ctor.rsplit(".", 1)[-1]
        # Second pass: resolve attr ctor names to project classes (all
        # classes exist by now) and index module import aliases.
        class_by_name: Dict[str, List[Tuple[str, str]]] = {}
        for (rel, name) in self.classes:
            class_by_name.setdefault(name, []).append((rel, name))
        for ci in self.classes.values():
            resolved = {}
            for attr, cname in ci.attr_types.items():
                hits = class_by_name.get(cname)
                if hits and len(hits) == 1:
                    resolved[attr] = hits[0]
            ci.attr_types = resolved
        self._module_aliases: Dict[Tuple[str, str], str] = {}
        by_tail: Dict[str, List[str]] = {}
        for m in self.modules:
            tail = m.rel.rsplit("/", 1)[-1][:-3]
            by_tail.setdefault(tail, []).append(m.rel)
        for m in self.modules:
            for node in ast.walk(m.tree):
                names = (node.names
                         if isinstance(node, (ast.Import, ast.ImportFrom))
                         else [])
                for alias in names:
                    tail = alias.name.rsplit(".", 1)[-1]
                    hits = by_tail.get(tail)
                    if hits and len(hits) == 1:
                        self._module_aliases[
                            (m.rel, alias.asname or tail)] = hits[0]

    @staticmethod
    def _lock_name(call: ast.Call, mod, cls: Optional[str],
                   attr: str) -> str:
        """Canonical lock id: the ``make_lock("name")`` string when present
        (lock NAMES are the ordering identity — every Client's
        ``client.put_batch`` is one role), else class-qualified attr."""
        if call.args:
            s = const_str(call.args[0])
            if s is not None:
                return s
        return f"{cls}.{attr}" if cls else f"{mod.rel}:{attr}"

    def _index_unique_methods(self):
        seen: Dict[str, List[FuncInfo]] = {}
        for (rel, cls, name), info in self._methods.items():
            seen.setdefault(name, []).append(info)
        for name, infos in seen.items():
            if len(infos) == 1 and len(name) >= 4 \
                    and name not in _COMMON_METHODS \
                    and not name.startswith("__"):
                self._unique_methods[name] = infos[0]
        # Unique lock ATTRS resolve foreign lock references
        # (self._client._put_batch_lock) to their canonical id.
        self._unique_lock_attrs: Dict[str, Tuple[str, str]] = {}
        counts: Dict[str, List[Tuple[str, str]]] = {}
        for ci in self.classes.values():
            for attr, lock_id in ci.lock_attrs.items():
                counts.setdefault(attr, []).append(
                    (lock_id, ci.lock_kinds[attr]))
        for attr, ids in counts.items():
            if len(ids) == 1:
                self._unique_lock_attrs[attr] = ids[0]

    # -- resolution ------------------------------------------------------------

    def _resolve_callable(self, expr, func: FuncInfo) -> Optional[FuncInfo]:
        """Resolve a callback/callee expression in ``func``'s scope."""
        if isinstance(expr, ast.Lambda):
            return self._by_node.get(id(expr))
        if isinstance(expr, ast.Call):
            # e.g. run_coroutine_threadsafe(self._connect(), loop): the
            # interesting target is the called coroutine function.
            return self._resolve_callable(expr.func, func)
        if isinstance(expr, ast.Name):
            cur = func
            while cur is not None:
                child = cur.children.get(expr.id)
                if child is not None:
                    return child
                cur = cur.parent
            if func.cls is not None:
                m = self._methods.get((func.module.rel, func.cls, expr.id))
                if m is not None:
                    return m
            return self._module_funcs.get((func.module.rel, expr.id))
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
                    and func.cls is not None:
                return self._methods.get(
                    (func.module.rel, func.cls, expr.attr))
            # Typed instance attribute: self.scheduler.acquire(...) where
            # __init__ assigned self.scheduler = ClusterScheduler(...).
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" and func.cls is not None:
                ci = self.classes.get((func.module.rel, func.cls))
                if ci is not None:
                    target = ci.attr_types.get(recv.attr)
                    if target is not None:
                        m = self._methods.get(
                            (target[0], target[1], expr.attr))
                        if m is not None:
                            return m
            # Imported project module: oref._flush_free_queue(...).
            if isinstance(recv, ast.Name):
                target_rel = self._module_aliases.get(
                    (func.module.rel, recv.id))
                if target_rel is not None:
                    m = self._module_funcs.get((target_rel, expr.attr))
                    if m is not None:
                        return m
            return self._unique_methods.get(expr.attr)
        return None

    def _resolve_lock(self, expr, func: FuncInfo) -> Optional[Tuple[str, str]]:
        """(lock_id, kind) for a with-item / guard expression, else None."""
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and func.cls is not None:
                ci = self.classes.get((func.module.rel, func.cls))
                if ci is not None and expr.attr in ci.lock_attrs:
                    return (ci.lock_attrs[expr.attr],
                            ci.lock_kinds[expr.attr])
            # Foreign receiver: unique lock attr across the tree.
            return self._unique_lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self._module_locks.get((func.module.rel, expr.id))
        return None

    # -- body extraction -------------------------------------------------------

    def _extract_bodies(self):
        for func in self.functions:
            self._walk_body(func, list(ast.iter_child_nodes(func.node)),
                            frozenset())

    def _walk_body(self, func: FuncInfo, nodes, held: FrozenSet[str]):
        for node in nodes:
            if isinstance(node, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
                # Separate execution context, cataloged on its own — but
                # remember what is held where it is DEFINED (sorted-key
                # lambdas and local helpers run right there).
                info = self._by_node.get(id(node))
                if info is not None:
                    info.def_site_held = held
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = set(held)
                for item in node.items:
                    lk = self._resolve_lock(item.context_expr, func)
                    if lk is not None:
                        self.acquisitions.append(Acquisition(
                            lk[0], lk[1], func, node.lineno,
                            frozenset(new)))
                        new.add(lk[0])
                # with-item expressions evaluate before the body holds.
                self._walk_body(
                    func, [i.context_expr for i in node.items], held)
                self._walk_body(func, node.body, frozenset(new))
                continue
            if isinstance(node, ast.Call):
                self._handle_call(func, node, held)
            self._record_access(func, node, held)
            self._walk_body(func, list(ast.iter_child_nodes(node)), held)

    def _handle_call(self, func: FuncInfo, call: ast.Call,
                     held: FrozenSet[str]):
        # The method name alone drives seed matching so chained receivers
        # (``asyncio.get_running_loop().call_soon(cb)``) still count.
        tail = (call.func.attr if isinstance(call.func, ast.Attribute)
                else call.func.id if isinstance(call.func, ast.Name)
                else None)
        # Role seeds: the argument callback runs under the seeded role.
        seeded = None
        if tail == "Thread":
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is not None:
                name_kw = next((const_str(kw.value) for kw in call.keywords
                                if kw.arg == "name"), None)
                cb = self._resolve_callable(target, func)
                if cb is not None:
                    role = f"thread:{name_kw}" if name_kw else (
                        f"thread:{cb.name}")
                    cb.role_seeds.add(role)
                    cb.has_caller = True
                    cb.entry_held = frozenset()
                    seeded = cb
        elif tail == "run_in_executor" and len(call.args) >= 2:
            cb = self._resolve_callable(call.args[1], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_EXECUTOR)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        elif tail == "submit" and call.args:
            cb = self._resolve_callable(call.args[0], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_EXECUTOR)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        elif tail in ("call_soon", "call_soon_threadsafe",
                      "add_done_callback") and call.args:
            cb = self._resolve_callable(call.args[0], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_LOOP)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        elif tail in ("call_later", "call_at") and len(call.args) >= 2:
            # loop.call_later(delay, cb, ...) / call_at(when, cb, ...):
            # the callback runs on the same loop thread as call_soon.
            cb = self._resolve_callable(call.args[1], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_LOOP)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        elif tail in ("on_push", "subscribe", "register", "handler") \
                and len(call.args) >= 2:
            cb = self._resolve_callable(call.args[1], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_LOOP)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        elif tail == "run_coroutine_threadsafe" and call.args:
            cb = self._resolve_callable(call.args[0], func)
            if cb is not None:
                cb.role_seeds.add(ROLE_LOOP)
                cb.has_caller = True
                cb.entry_held = frozenset()
                seeded = cb
        # Direct call edge (not for seeded registrations: registering a
        # callback is not calling it here).
        callee = self._resolve_callable(call.func, func)
        if callee is not None and callee is not seeded:
            callee.has_caller = True
            self.call_sites.append(
                CallSite(callee, func, call.lineno, held))

    def _record_access(self, func: FuncInfo, node, held: FrozenSet[str]):
        """self.<attr> loads/stores, classifying writes (attr rebinds,
        subscript stores through the attr, mutator method calls)."""
        if func.cls is None:
            return
        cls_key = (func.module.rel, func.cls)
        targets: List[Tuple[ast.Attribute, str]] = []
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets.extend(self._attr_targets(t, "write"))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets.extend(self._attr_targets(node.target, "write"))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                targets.extend(self._attr_targets(t, "write"))
        elif isinstance(node, ast.Call):
            # self._x.append(...) and friends.
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                    and isinstance(f.value, ast.Attribute) \
                    and isinstance(f.value.value, ast.Name) \
                    and f.value.value.id == "self":
                targets.append((f.value, "write"))
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load):
            targets.append((node, "read"))
        for attr_node, kind in targets:
            self.accesses.append(Access(
                cls_key, attr_node.attr, kind, func,
                attr_node.lineno, held))

    @staticmethod
    def _attr_targets(t, kind) -> List[Tuple[ast.Attribute, str]]:
        out = []
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            out.append((t, kind))
        elif isinstance(t, ast.Subscript):
            v = t.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.append((v, kind))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                out.extend(ConcurrencyModel._attr_targets(el, kind))
        return out

    # -- role propagation ------------------------------------------------------

    def _propagate_roles(self):
        # Entries: TOP-LEVEL functions/methods nothing resolvable calls and
        # nothing seeds run on whatever thread the user calls them from.
        # A NESTED orphan (sorted key, local helper) instead runs where it
        # is defined: it inherits the enclosing function's roles.
        orphans: List[FuncInfo] = []
        for f in self.functions:
            f.roles |= f.role_seeds
            if not f.role_seeds and not f.has_caller:
                if f.parent is None:
                    f.roles.add(ROLE_MAIN)
                    f.entry_held = frozenset()
                else:
                    orphans.append(f)
        # Roles flow caller -> callee along direct call edges, EXCEPT into
        # async defs: calling a coroutine function schedules it on a loop,
        # it does not run it on the calling thread.
        edges: Dict[FuncInfo, Set[FuncInfo]] = {}
        for cs in self.call_sites:
            if cs.callee.is_async:
                continue
            edges.setdefault(cs.func, set()).add(cs.callee)
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for caller, callees in edges.items():
                if not caller.roles:
                    continue
                for callee in callees:
                    if not caller.roles <= callee.roles:
                        callee.roles |= caller.roles
                        changed = True
            for f in orphans:
                if not f.parent.roles <= f.roles:
                    f.roles |= f.parent.roles
                    changed = True
        self._orphans = orphans

    def _solve_entry_held(self):
        """Locks provably held at ENTRY of each function: the intersection
        over its call sites of (caller entry_held + lexical held at the
        site).  Seeded callbacks and entries start with nothing held."""
        incoming: Dict[FuncInfo, List[CallSite]] = {}
        for cs in self.call_sites:
            incoming.setdefault(cs.callee, []).append(cs)
        # Functions with no known call site are entries: they start with
        # nothing held.  Without this pin the fixpoint never seeds — every
        # chain rooted at an entry would stay "unknown" and default to
        # nothing-held, erasing provable Lock-held-on-entry facts.
        for f in self.functions:
            if f.entry_held is None and f not in incoming:
                f.entry_held = frozenset()
        for _ in range(20):
            changed = False
            for callee, sites in incoming.items():
                if callee.entry_held == frozenset():
                    continue  # pinned: entry/seeded callback
                met: Optional[FrozenSet[str]] = None
                unknown = False
                for cs in sites:
                    base = cs.func.entry_held
                    if base is None:
                        unknown = True
                        continue
                    eff = cs.held | base
                    met = eff if met is None else (met & eff)
                if unknown and met is None:
                    continue
                if met is None:
                    met = frozenset()
                if callee.entry_held != met:
                    callee.entry_held = met
                    changed = True
            if not changed:
                break
        for f in self.functions:
            if f.entry_held is None:
                f.entry_held = frozenset()
        # Nested orphans execute where they were defined: what the parent
        # held there is held for them too.
        for _ in range(5):
            changed = False
            for f in self._orphans:
                inherited = (f.parent.entry_held or frozenset()) \
                    | f.def_site_held
                if inherited - (f.entry_held or frozenset()):
                    f.entry_held = (f.entry_held or frozenset()) | inherited
                    changed = True
            if not changed:
                break

    # -- derived views ---------------------------------------------------------

    def class_accesses(self) -> Dict[Tuple[str, str], Dict[str, List[Access]]]:
        out: Dict[Tuple[str, str], Dict[str, List[Access]]] = {}
        for a in self.accesses:
            out.setdefault(a.cls_key, {}).setdefault(a.attr, []).append(a)
        return out

    def unguarded_annotation(self, module, line) -> Optional[str]:
        return _line_annotation(module, line, _UNGUARDED_RE)

    def infer_guard(self, accesses: List[Access]) -> Optional[str]:
        """The lock (if any) held at EVERY access — the inferred guard."""
        met: Optional[FrozenSet[str]] = None
        for a in accesses:
            eff = a.effective_held()
            met = eff if met is None else (met & eff)
            if not met:
                return None
        if met:
            return sorted(met)[0]
        return None

    def lock_order_edges(self) -> Dict[Tuple[str, str], Tuple[str, int]]:
        """(outer, inner) -> first (module_rel, line) establishing it.
        Composes nested ``with`` scopes through the call graph: a call made
        while holding A to a function that (transitively) acquires B is an
        A -> B edge, exactly like a lexical nesting."""
        # Transitively acquired thread-lock sets per function.
        acquired: Dict[FuncInfo, Set[str]] = {f: set() for f in self.functions}
        for acq in self.acquisitions:
            if acq.kind == "thread":
                acquired[acq.func].add(acq.lock)
        callees: Dict[FuncInfo, Set[FuncInfo]] = {}
        for cs in self.call_sites:
            callees.setdefault(cs.func, set()).add(cs.callee)
        for _ in range(30):
            changed = False
            for f, cs in callees.items():
                for c in cs:
                    if not acquired[c] <= acquired[f]:
                        acquired[f] |= acquired[c]
                        changed = True
            if not changed:
                break
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for acq in self.acquisitions:
            if acq.kind != "thread":
                continue
            outer = acq.held | (acq.func.entry_held or frozenset())
            for o in outer:
                if o != acq.lock:
                    edges.setdefault((o, acq.lock),
                                     (acq.func.module.rel, acq.line))
        for cs in self.call_sites:
            outer = cs.held | (cs.func.entry_held or frozenset())
            if not outer:
                continue
            for inner in acquired.get(cs.callee, ()):
                for o in outer:
                    if o != inner:
                        edges.setdefault(
                            (o, inner), (cs.func.module.rel, cs.line))
        return edges
