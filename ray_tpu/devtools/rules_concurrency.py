"""RT007/RT008: interprocedural concurrency analysis over ``core/``.

The reference catches this bug class with TSAN + C++ annotations
(``GUARDED_BY``, reference: src/ray/util/mutex_protected.h and the
sanitizer CI).  Here the control plane is pure Python mutated from the
head loop, the shared peer-loop thread, RPC reader callbacks, executors,
and throwaway offload threads — so rtlint rebuilds the same protection
statically on the :class:`~.astutil.ConcurrencyModel` (thread-role
inference + guard-map inference + lock composition through the call
graph):

RT007 — **guarded-by races**: a ``self.<attr>`` written from two or more
thread roles where some access path holds no lock in common with the
write.  Classes may declare ``_RT_GUARDED_BY = {"attr": "_lock_attr"}``
(verified here, enforced at runtime by ``devtools.locks`` under
``RT_DEBUG_LOCKS=2``) and vet intentional handoffs via
``_RT_UNGUARDED = {"attr": "reason"}`` or a trailing
``# rt-unguarded: reason`` comment.

RT008 — **static lock-order cycles**: ``with lock:`` scopes composed
through the call graph form an ordering digraph; any cycle is a deadlock
waiting for the right interleaving — found at lint time instead of by the
runtime sentinel happening to hit the inversion.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .astutil import ConcurrencyModel
from .rtlint import Finding, Project


def _scope(project: Project):
    """The analyzed modules: ``core/`` when the tree has one (the real
    package), else every module (synthetic rule-test trees)."""
    core = [m for m in project.modules
            if "/core/" in m.rel or m.rel.startswith("core/")]
    return core if core else list(project.modules)


def _model(project: Project) -> ConcurrencyModel:
    cached = getattr(project, "_concurrency_model", None)
    if cached is None:
        cached = project._concurrency_model = ConcurrencyModel(
            _scope(project))
    return cached


# -- RT007 ---------------------------------------------------------------------


def check_rt007(project: Project) -> List[Finding]:
    model = _model(project)
    out: List[Finding] = []
    for cls_key, attrs in sorted(model.class_accesses().items()):
        ci = model.classes.get(cls_key)
        for attr, accesses in sorted(attrs.items()):
            if attr.startswith("__"):
                continue
            if ci is not None and (attr in ci.lock_attrs
                                   or attr in ci.threadsafe_attrs):
                continue
            declared = ci.guarded_by.get(attr) if ci is not None else None
            if declared is not None:
                out.extend(_check_declared(model, ci, attr, declared,
                                           accesses))
                continue
            if ci is not None and attr in ci.unguarded:
                continue
            if any(model.unguarded_annotation(a.func.module, a.line)
                   for a in accesses):
                continue
            f = _check_inferred(model, cls_key, attr, accesses)
            if f is not None:
                out.append(f)
    # Declared guards must reference real lock attributes, and dead
    # _RT_UNGUARDED rows are stale vetting (mirror the allowlist rule).
    by_class = model.class_accesses()
    for ci in model.classes.values():
        for attr, lock_attr in sorted(ci.guarded_by.items()):
            if lock_attr not in ci.lock_attrs:
                out.append(Finding(
                    "RT007", ci.module.rel, ci.lineno,
                    f"{ci.name}._RT_GUARDED_BY maps {attr!r} to "
                    f"{lock_attr!r}, which is not a lock attribute of "
                    f"{ci.name} — the runtime sentinel cannot enforce it",
                    meta={"class": ci.name, "attr": attr,
                          "guard": lock_attr, "kind": "bad-guard"}))
        for attr in sorted(ci.unguarded):
            if attr not in by_class.get(ci.key, {}):
                out.append(Finding(
                    "RT007", ci.module.rel, ci.lineno,
                    f"{ci.name}._RT_UNGUARDED vets {attr!r} but nothing "
                    "accesses it — stale vetting, remove the entry",
                    meta={"class": ci.name, "attr": attr, "kind": "stale"}))
    return out


def _check_declared(model, ci, attr, lock_attr, accesses) -> List[Finding]:
    """Writes to a declared-guarded field must hold the declared lock —
    the static twin of the RT_DEBUG_LOCKS=2 runtime assertion."""
    lock_id = ci.lock_attrs.get(lock_attr)
    if lock_id is None:
        return []  # reported as bad-guard above
    out = []
    for a in accesses:
        if a.kind != "write" or a.func.name == "__init__":
            continue
        if lock_id in a.effective_held():
            continue
        if model.unguarded_annotation(a.func.module, a.line):
            continue
        out.append(Finding(
            "RT007", a.func.module.rel, a.line,
            f"{ci.name}.{attr} is declared guarded by {lock_attr!r} "
            f"({lock_id!r}) but this write in {a.func.qualname} "
            f"(roles: {_roles(a.func.roles)}) does not hold it",
            meta={"class": ci.name, "attr": attr, "guard": lock_id,
                  "roles": sorted(a.func.roles), "kind": "declared"}))
    return out


def _roles(roles: Set[str]) -> str:
    return "/".join(sorted(roles)) if roles else "<unreached>"


def _check_inferred(model, cls_key, attr,
                    accesses) -> Optional[Finding]:
    live = [a for a in accesses
            if a.func.name != "__init__" and a.func.roles]
    writes = [a for a in live if a.kind == "write"]
    if not writes:
        return None  # set once in __init__, read-only after publication
    roles: Set[str] = set()
    for a in live:
        roles |= a.func.roles
    if len(roles) < 2:
        return None  # single thread class: confined state
    guard = model.infer_guard(live)
    if guard is not None:
        return None  # consistently guarded
    # Find a concrete racing pair: a write and another access on distinct
    # roles with no lock in common (a write whose own function runs under
    # two roles races with itself).
    for w in writes:
        for a in live:
            pair_roles = w.func.roles | a.func.roles
            if len(pair_roles) < 2:
                continue
            if w.effective_held() & a.effective_held():
                continue
            cls = cls_key[1]
            mostly = model.infer_guard(
                [x for x in live if x is not w and x is not a])
            hint = (f"; other accesses hold {mostly!r} — guard this one too"
                    if mostly else "")
            return Finding(
                "RT007", w.func.module.rel, w.line,
                f"{cls}.{attr} is written in {w.func.qualname} (roles: "
                f"{_roles(w.func.roles)}) with no lock in common with the "
                f"access in {a.func.qualname} at line {a.line} (roles: "
                f"{_roles(a.func.roles)}) — unguarded cross-thread "
                f"state{hint}",
                meta={"class": cls, "attr": attr,
                      "roles": sorted(roles),
                      "write_roles": sorted(w.func.roles),
                      "other_roles": sorted(a.func.roles),
                      "other_line": a.line,
                      "write_held": sorted(w.effective_held()),
                      "other_held": sorted(a.effective_held()),
                      "kind": "race"})
    return None


# -- RT008 ---------------------------------------------------------------------


def check_rt008(project: Project) -> List[Finding]:
    model = _model(project)
    edges = model.lock_order_edges()
    adj: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        nodes.update((a, b))
    out: List[Finding] = []
    # One finding per strongly-connected component: composition through
    # the call graph derives shortcut edges (A held while calling into a
    # B-then-C chain yields A->C too), so one inconsistent cluster would
    # otherwise surface as several overlapping cycles.
    for scc in _sccs(nodes, adj):
        if len(scc) < 2:
            continue
        a = sorted(scc)[0]
        nxt = next(b for b in adj.get(a, ()) if b in scc)
        path = _path({k: [v for v in vs if v in scc]
                      for k, vs in adj.items()}, nxt, a)
        # _path ends at `a`; drop it — the cycle renders its own closure.
        cycle = [a] + (path[:-1] if path else [nxt])
        rel, line = edges[(cycle[0], cycle[1])]
        sites = {f"{x} -> {y}": "%s:%d" % edges[(x, y)]
                 for x, y in zip(cycle, cycle[1:] + [cycle[0]])
                 if (x, y) in edges}
        out.append(Finding(
            "RT008", rel, line,
            "static lock-order cycle among "
            + "/".join(repr(s) for s in sorted(scc)) + ": "
            + " -> ".join(repr(c) for c in cycle + [cycle[0]])
            + " — these locks nest in both orders somewhere in the call "
            "graph (" + ", ".join(f"{k} at {v}" for k, v in sites.items())
            + "); a matching interleaving deadlocks",
            meta={"locks": sorted(scc), "cycle": cycle, "sites": sites,
                  "kind": "lock-cycle"}))
    out.sort(key=Finding.key)
    return out


def _sccs(nodes: Set[str], adj: Dict[str, List[str]]) -> List[Set[str]]:
    """Kosaraju: strongly-connected components of the ordering digraph."""
    order: List[str] = []
    seen: Set[str] = set()
    for start in sorted(nodes):
        if start in seen:
            continue
        stack = [(start, iter(adj.get(start, ())))]
        seen.add(start)
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    advanced = True
                    break
            if not advanced:
                order.append(node)
                stack.pop()
    radj: Dict[str, List[str]] = {}
    for a, bs in adj.items():
        for b in bs:
            radj.setdefault(b, []).append(a)
    sccs: List[Set[str]] = []
    assigned: Set[str] = set()
    for node in reversed(order):
        if node in assigned:
            continue
        comp = {node}
        queue = [node]
        assigned.add(node)
        while queue:
            cur = queue.pop()
            for nxt in radj.get(cur, ()):
                if nxt not in assigned:
                    assigned.add(nxt)
                    comp.add(nxt)
                    queue.append(nxt)
        sccs.append(comp)
    return sccs


def _path(adj: Dict[str, List[str]], src: str,
          dst: str) -> Optional[List[str]]:
    prev: Dict[str, Optional[str]] = {src: None}
    queue = [src]
    while queue:
        cur = queue.pop(0)
        if cur == dst:
            path = []
            node: Optional[str] = cur
            while node is not None:
                path.append(node)
                node = prev[node]
            return list(reversed(path))
        for nxt in adj.get(cur, ()):
            if nxt not in prev:
                prev[nxt] = cur
                queue.append(nxt)
    return None
