"""RT004: user-facing remote-function footguns.

Two patterns that work in toy runs and bite at scale:

- ``ray_tpu.get()`` inside a remote function body: the worker parks in a
  blocking get while holding its pool slot; deep enough nesting (or an
  actor awaiting its own queue) deadlocks the cluster.  The framework
  mitigates plain-task nesting via ``task_blocked`` resource release, but
  every such site deserves a look — vetted ones go in the allowlist.
- closure captures in nested remote functions: captured values are
  serialized into the function blob and re-shipped on every submission;
  a captured array silently multiplies submission cost.  Pass data as
  arguments (object-store refs ship once) instead.
"""

from __future__ import annotations

import ast
import builtins
from typing import List

from .astutil import (call_name, decorator_names, enclosing_functions,
                      local_names, module_scope_names, parent_map,
                      walk_own_body)
from .rtlint import Finding, Project

GET_CALLS = {"ray_tpu.get", "api.get", "rt.get"}
REMOTE_DECORATORS = {"remote", "ray_tpu.remote", "api.remote"}
_BUILTINS = set(dir(builtins))


def _remote_defs(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if any(d in REMOTE_DECORATORS for d in decorator_names(node)):
                yield node


def check_rt004(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for module in project.modules:
        parents = parent_map(module.tree)
        mod_names = module_scope_names(module.tree)
        for rdef in _remote_defs(module.tree):
            # -- nested get anywhere in the remote body -----------------------
            for node in ast.walk(rdef):
                if isinstance(node, ast.Call) \
                        and call_name(node) in GET_CALLS:
                    out.append(Finding(
                        "RT004", module.rel, node.lineno,
                        f"ray_tpu.get() inside remote {rdef.name!r} — "
                        "nested blocking get; prefer passing refs as "
                        "arguments (auto-resolved) or restructuring to "
                        "avoid the worker parking on the result",
                    ))
            # -- closure captures in nested remote functions ------------------
            if isinstance(rdef, ast.ClassDef):
                continue
            enclosing = enclosing_functions(rdef, parents)
            if not enclosing:
                continue
            own = local_names(rdef)
            captured = set()
            for node in walk_own_body(rdef):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id not in own \
                        and node.id not in mod_names \
                        and node.id not in _BUILTINS:
                    for encl in enclosing:
                        if node.id in local_names(encl):
                            captured.add(node.id)
                            break
            if captured:
                out.append(Finding(
                    "RT004", module.rel, rdef.lineno,
                    f"remote {rdef.name!r} captures enclosing-scope "
                    f"variable(s) {sorted(captured)} — captures are "
                    "serialized into the function blob and re-shipped on "
                    "every submission; pass them as arguments or "
                    "ray_tpu.put() them once",
                ))
    return out
