"""rtlint: framework-aware static analysis for the ray_tpu control plane.

Generic linters can't know that ``core/head.py`` is a single asyncio loop
whose handlers must never block, that every ``client.call("m")`` string
must have an ``h_m`` handler and (when mutating) a ``schema.REQUIRED``
row, or that ``ray_tpu_*`` metric names must match the catalog in
``util/metrics.py``.  rtlint does — it walks the package with ``ast``
(nothing is imported or executed) and enforces:

======  =====================================================================
RT001   blocking call (``time.sleep``, ``subprocess.*``, socket
        recv/sendall, sync ``rpc.call``, file reads, ``shutil.rmtree``)
        inside an ``async def`` — stalls the whole control plane
RT002   ``threading`` lock held across an ``await`` (with-block containing
        ``await`` under a lock) — cross-thread deadlock / loop stall
RT003   RPC drift: client-called method without an ``h_*`` handler in
        head/node, mutating client method without a ``schema.REQUIRED``
        row, schema row without a handler, handler nothing calls
RT004   ``ray_tpu.get()`` inside a remote function body (nested-get
        deadlock risk) and closure captures in nested remote functions
        (re-shipped on every submission)
RT005   ``threading.Thread`` started without ``daemon=True`` or a visible
        join path — leaks non-daemon threads that hang interpreter exit
RT006   ``ray_tpu_*`` metric emitted but missing from (or conflicting
        with) the ``BUILTIN_METRICS`` catalog in ``util/metrics.py``
RT007   guarded-by race over ``core/``: a ``self.<attr>`` written from two
        or more inferred thread roles (loop / rpc callbacks / executors /
        named threads / main) with no lock in common across access paths;
        also verifies declared ``_RT_GUARDED_BY`` maps (the runtime race
        sentinel enforces the same maps under ``RT_DEBUG_LOCKS=2``)
RT008   static lock-order cycle: nested ``with lock:`` scopes composed
        through the call graph nest in both orders — a deadlock the test
        suite merely never interleaved
RT009   spawn-env contract drift: ad-hoc ``RT_*`` ``os.environ`` reads vs
        the ``SPAWN_ENV_CONTRACT`` catalog in ``core/config.py``
        (missing/stale/orphan-write, plus reads shadowing Config fields)
RT010   JAX hot-path hazards: recompile triggers (jit-in-loop defs,
        unhashable static args), implicit host syncs (``.item()`` /
        ``float()`` / ``np.asarray`` on jit outputs) inside the step
        loops, and donated buffers read after the donating call —
        vetted per-line with ``# rt-sync-ok: <reason>``; the runtime
        half is the ``RT_DEBUG_JIT=1`` recompile sentinel
        (``devtools.jitguard``)
RT011   resource-lifecycle leaks over the declared acquire/release pair
        catalog (page alloc/free, adapter pin/release, prefix claims,
        scheduler leases): leaks on normal and exception exits, double
        releases, releases of never-acquired names — ownership
        transfers annotated ``# rt-owns: <pair>``
RT012   deadline-contract drift: hand-rolled retry curves instead of
        ``core.deadline.BackoffPolicy``, unbounded ``while True``
        re-dial loops with no ``Deadline``, and sentinel
        ``timeout=1e9``-style constants — vetted per-line with
        ``# rt-deadline-ok: <reason>``
======  =====================================================================

Vetted exceptions live in ``ray_tpu/.rtlint-allowlist`` (shipped as
package data; one
``RULE path[:line]  # reason`` per line; the reason is mandatory).  The
pytest gate ``tests/test_rtlint.py::test_package_lint_clean`` runs this
over the tree, so unallowlisted findings fail CI.

Usage::

    python -m ray_tpu lint [--json] [--root DIR] [--allowlist FILE]
"""

from __future__ import annotations

import ast
import fnmatch
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass
class Finding:
    rule: str
    path: str  # posix path relative to the package parent (repo-relative)
    line: int
    message: str
    #: structured context for --json consumers (dashboard lint view,
    #: future tooling): RT007 carries the inferred thread roles and guard
    #: locks behind the race, RT008 the lock cycle and its edge sites,
    #: RT009 the env key and drift kind — the WHY, not just the where.
    meta: Optional[dict] = None

    def key(self) -> tuple:
        return (self.rule, self.path, self.line, self.message)

    def as_dict(self) -> dict:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "message": self.message}
        if self.meta is not None:
            out["meta"] = self.meta
        return out


class Module:
    def __init__(self, path: Path, rel: str, source: str, tree: ast.Module):
        self.path = path
        self.rel = rel  # e.g. "ray_tpu/core/head.py"
        self.source = source
        self.tree = tree


class Project:
    """Parsed view of one package tree.  ``package_root`` is the package
    directory itself (the directory containing ``core/``); reported paths
    are prefixed with its name so findings read repo-relative."""

    def __init__(self, package_root: Path):
        self.package_root = Path(package_root)
        self.modules: List[Module] = []
        self.parse_errors: List[Finding] = []
        prefix = self.package_root.name
        for path in sorted(self.package_root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = f"{prefix}/{path.relative_to(self.package_root).as_posix()}"
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except (SyntaxError, UnicodeDecodeError) as e:
                line = getattr(e, "lineno", 0) or 0
                self.parse_errors.append(
                    Finding("RT000", rel, line, f"unparseable module: {e}")
                )
                continue
            self.modules.append(Module(path, rel, source, tree))

    def find(self, suffix: str) -> Optional[Module]:
        """Module whose repo-relative path ends with ``suffix`` (e.g.
        ``core/client.py``) — layout-independent so rules work over both
        the real package and synthetic test trees."""
        for m in self.modules:
            if m.rel.endswith(suffix):
                return m
        return None


# -- allowlist -----------------------------------------------------------------


@dataclass
class AllowEntry:
    rule: str
    pattern: str  # fnmatch pattern over the finding's repo-relative path
    line: Optional[int]
    reason: str
    lineno: int  # where in the allowlist file
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        return (
            self.rule == f.rule
            and fnmatch.fnmatch(f.path, self.pattern)
            and (self.line is None or self.line == f.line)
        )


def load_allowlist(path: Path) -> Tuple[List[AllowEntry], List[Finding]]:
    """Parse ``RULE path[:line]  # reason`` lines.  Malformed entries (and
    entries with no reason — every exception must be justified) surface as
    findings so they can't silently disable a rule."""
    entries: List[AllowEntry] = []
    problems: List[Finding] = []
    rel = path.name
    if not path.exists():
        return entries, problems
    for lineno, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        body, _, reason = line.partition("#")
        reason = reason.strip()
        parts = body.split()
        if len(parts) != 2 or not parts[0].startswith("RT"):
            problems.append(Finding(
                "ALLOWLIST", rel, lineno,
                f"malformed entry {line!r} (expected 'RTnnn path[:line]"
                f"  # reason')"))
            continue
        if not reason:
            problems.append(Finding(
                "ALLOWLIST", rel, lineno,
                f"entry {body.strip()!r} has no '# reason' — every "
                "allowlisted exception must be justified"))
            continue
        rule, target = parts
        pat, sep, ln = target.rpartition(":")
        entry_line: Optional[int] = None
        if sep and ln.isdigit():
            entry_line = int(ln)
        else:
            pat = target
        entries.append(AllowEntry(rule, pat, entry_line, reason, lineno))
    return entries, problems


def apply_allowlist(
    findings: List[Finding], entries: List[AllowEntry], allow_name: str
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed); stale entries that matched
    nothing come back as kept ALLOWLIST findings — the allowlist must
    shrink when the code it excuses is fixed."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        entry = next((e for e in entries if e.matches(f)), None)
        if entry is not None:
            entry.hits += 1
            suppressed.append(f)
        else:
            kept.append(f)
    for e in entries:
        if e.hits == 0:
            kept.append(Finding(
                "ALLOWLIST", allow_name, e.lineno,
                f"stale entry '{e.rule} {e.pattern}"
                f"{':%d' % e.line if e.line else ''}' matched no finding — "
                "remove it"))
    return kept, suppressed


# -- engine --------------------------------------------------------------------


def all_rules():
    from . import (rules_api, rules_async, rules_concurrency, rules_config,
                   rules_deadline, rules_jax, rules_metrics, rules_resources,
                   rules_rpc, rules_threads)

    return [
        rules_async.check_rt001,
        rules_async.check_rt002,
        rules_rpc.check_rt003,
        rules_api.check_rt004,
        rules_threads.check_rt005,
        rules_metrics.check_rt006,
        rules_concurrency.check_rt007,
        rules_concurrency.check_rt008,
        rules_config.check_rt009,
        rules_jax.check_rt010,
        rules_resources.check_rt011,
        rules_deadline.check_rt012,
    ]


def run_lint(
    package_root: Path,
    allowlist_path: Optional[Path] = None,
    rules=None,
) -> Tuple[List[Finding], List[Finding]]:
    """Lint a package tree.  Returns ``(kept, suppressed)`` — kept findings
    (including allowlist problems) mean failure."""
    project = Project(Path(package_root))
    findings: List[Finding] = list(project.parse_errors)
    for rule in (rules if rules is not None else all_rules()):
        findings.extend(rule(project))
    findings.sort(key=Finding.key)
    entries: List[AllowEntry] = []
    problems: List[Finding] = []
    if allowlist_path is not None:
        entries, problems = load_allowlist(Path(allowlist_path))
    kept, suppressed = apply_allowlist(
        findings, entries,
        allowlist_path.name if allowlist_path is not None else "",
    )
    kept.extend(problems)
    kept.sort(key=Finding.key)
    return kept, suppressed


def default_package_root() -> Path:
    return Path(__file__).resolve().parent.parent


def default_allowlist(package_root: Path) -> Path:
    # Inside the package (shipped as package data), so the CLI works on an
    # installed wheel, not only a repo checkout.
    return Path(package_root) / ".rtlint-allowlist"


def render_table(kept: Sequence[Finding],
                 suppressed: Sequence[Finding]) -> str:
    lines: List[str] = []
    for f in kept:
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
    tail = (f"{len(kept)} finding(s)"
            if kept else "rtlint: no findings")
    if suppressed:
        tail += f" ({len(suppressed)} allowlisted)"
    lines.append(tail)
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="ray_tpu lint",
        description="framework-aware static analysis (rules RT001-RT012)",
    )
    ap.add_argument("--root", default=None,
                    help="package directory to lint (default: the "
                         "installed ray_tpu package)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: the package's own "
                         ".rtlint-allowlist; pass /dev/null for none)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    args = ap.parse_args(argv)
    root = Path(args.root) if args.root else default_package_root()
    if not root.is_dir():
        print(f"rtlint: no such package directory: {root}")
        return 2
    allow = (Path(args.allowlist) if args.allowlist
             else default_allowlist(root))
    kept, suppressed = run_lint(root, allow)
    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in kept],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=1))
    else:
        print(render_table(kept, suppressed))
    return 1 if kept else 0


if __name__ == "__main__":
    raise SystemExit(main())
