"""RT011: resource-lifecycle leak detection.

The serving/scheduling planes are built on manually paired
acquire/release protocols — KV pages out of the :class:`PageAllocator`,
LoRA slot pins in the :class:`AdapterPool`, prefix-cache page claims,
scheduler slot leases.  A path that acquires and does not release does
not crash: it strands capacity until the pool is exhausted and admission
wedges (the leak shows up hours later as "engine stopped admitting").

The declared pair catalog below is checked per function,
statement-block-sensitively:

- **leak** — an acquire whose result neither escapes the function
  (returned, stored into an attribute of self/a parameter — the
  request-object ownership handoff) nor reaches any release of the same
  pair.  Intentional transfers carry ``# rt-owns: <pair>`` on the
  acquire line.
- **exception-path leak** — the release exists but only on the fall-
  through path: nothing between acquire and release is try/finally- or
  with-protected, so a raise in between strands the resource.  A
  release inside a ``finally`` or an ``except`` handler whose ``try``
  covers the acquire satisfies both exits.
- **double release** — two releases of the same value in one statement
  block with no intervening acquire/rebind (a double ``free`` corrupts
  the allocator's refcounts silently).
- **release-without-acquire** — releasing a bare local name the
  function never bound: there is nothing to release (typo or stale
  refactor).

``--json`` meta names the pair and both site lists so the dashboard
lint view can render the unbalanced protocol directly.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .astutil import dotted_name, walk_own_body, _line_annotation
from .rtlint import Finding, Project

_OWNS_RE = re.compile(r"#\s*rt-owns:\s*([A-Za-z0-9_\-]+)")

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: The lifecycle catalog: method-name pairs plus a receiver hint — a
#: lowercase substring the receiver's dotted path must contain, so an
#: unrelated ``options.release()`` never matches ``adapter_pool.release``.
#: (name, acquire methods, release methods, receiver hints)
_RT_RESOURCE_PAIRS: Tuple[Tuple[str, Tuple[str, ...], Tuple[str, ...],
                                Tuple[str, ...]], ...] = (
    ("kv_pages", ("alloc",), ("free",), ("alloc",)),
    ("prefix_claim", ("claim",), ("free", "decref"), ("cache", "alloc")),
    ("adapter_pin", ("reserve", "acquire"), ("release",),
     ("adapter", "pool")),
    ("sched_slot", ("lease_slot",), ("release_slot", "revoke"),
     ("scheduler", "sched")),
    ("tpu_chips", ("allocate_tpu_chips",), ("free_tpu_chips",),
     ("scheduler", "sched")),
)


class _Site:
    __slots__ = ("call", "line", "kind", "pair", "recv", "bound")

    def __init__(self, call, kind, pair, recv, bound):
        self.call = call
        self.line = call.lineno
        self.kind = kind      # "acquire" | "release"
        self.pair = pair      # pair name
        self.recv = recv      # receiver dotted name ("self.allocator")
        self.bound = bound    # name the acquire result is bound to, or None


def _match_pair(call: ast.Call) -> Optional[Tuple[str, str, str]]:
    """(pair_name, kind, receiver) when the call is a cataloged
    acquire/release, else None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = dotted_name(f.value) or ""
    low = recv.lower()
    for name, acq, rel, hints in _RT_RESOURCE_PAIRS:
        if not any(h in low for h in hints):
            continue
        if f.attr in acq:
            return (name, "acquire", recv)
        if f.attr in rel:
            return (name, "release", recv)
    return None


def _first_arg_name(call: ast.Call) -> Optional[str]:
    """Bare-name (or dotted) identity of a release's subject."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, (ast.List, ast.Tuple)) and len(arg.elts) == 1:
        arg = arg.elts[0]
    return dotted_name(arg)


def _stmt_of(node: ast.AST, pmap: Dict) -> Optional[ast.stmt]:
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = pmap.get(cur)
    return cur


def _enclosing(node: ast.AST, pmap: Dict, func_node: ast.AST,
               kinds) -> List[ast.AST]:
    out = []
    cur = pmap.get(node)
    while cur is not None and cur is not func_node:
        if isinstance(cur, kinds):
            out.append(cur)
        if isinstance(cur, _FUNC_NODES):
            break
        cur = pmap.get(cur)
    return out


def _is_protected_release(site: _Site, pmap, func_node) -> bool:
    """Release reached on the exception exit too: inside a ``finally``
    or an ``except`` handler."""
    cur = pmap.get(site.call)
    child = site.call
    while cur is not None and cur is not func_node:
        if isinstance(cur, ast.Try):
            if child in getattr(cur, "finalbody", []):
                return True
        if isinstance(cur, ast.ExceptHandler):
            return True
        if isinstance(cur, _FUNC_NODES):
            break
        child, cur = cur, pmap.get(cur)
    # Walk again statement-wise: the direct child tracking above only
    # sees immediate members; check all finalbody containment.
    cur = pmap.get(site.call)
    prev = site.call
    while cur is not None and cur is not func_node:
        if isinstance(cur, ast.Try) and any(
                prev is s or _contains(s, prev) for s in cur.finalbody):
            return True
        if isinstance(cur, _FUNC_NODES):
            break
        prev, cur = cur, pmap.get(cur)
    return False


def _contains(root: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(root))


def _escapes(site: _Site, func_node: ast.AST, pmap) -> bool:
    """Does the acquired resource's ownership leave this function by a
    sanctioned route?  (a) the acquire result is returned; (b) it is
    assigned to an attribute (``req.pages = ...`` / ``self.x = ...`` —
    the object now owns it); (c) the bound name is later returned or
    attribute-assigned."""
    parent = pmap.get(site.call)
    while parent is not None and not isinstance(parent, ast.stmt):
        if isinstance(parent, ast.Return):
            return True
        parent = pmap.get(parent)
    stmt = _stmt_of(site.call, pmap)
    if isinstance(stmt, ast.Return):
        return True
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Attribute):
                return True
            if isinstance(t, ast.Subscript):
                return True
    if site.bound is None:
        return False
    for node in walk_own_body(func_node):
        if isinstance(node, ast.Return) and node.value is not None:
            if site.bound in {n.id for n in ast.walk(node.value)
                              if isinstance(n, ast.Name)}:
                return True
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in node.targets):
            if site.bound in {n.id for n in ast.walk(node.value)
                              if isinstance(n, ast.Name)}:
                return True
        # Ownership also escapes through a call handoff the analysis
        # cannot see into (self._fail(req, pages) etc.) — only when the
        # bound name is an ARGUMENT of a non-release call.
        if isinstance(node, ast.Call) and _match_pair(node) is None:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == site.bound:
                    return True
    return False


def check_rt011(project: Project) -> List[Finding]:
    out: List[Finding] = []
    from .astutil import parent_map, iter_functions

    for mod in project.modules:
        pmap = parent_map(mod.tree)
        for fn in iter_functions(mod.tree):
            sites: List[_Site] = []
            for node in walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                m = _match_pair(node)
                if m is None:
                    continue
                pair, kind, recv = m
                bound = None
                if kind == "acquire":
                    stmt = _stmt_of(node, pmap)
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name):
                        bound = stmt.targets[0].id
                sites.append(_Site(node, kind, pair, recv, bound))
            if not sites:
                continue
            by_pair: Dict[str, List[_Site]] = {}
            for s in sites:
                by_pair.setdefault(s.pair, []).append(s)
            for pair, ps in sorted(by_pair.items()):
                out.extend(_check_function_pair(mod, fn, pmap, pair, ps))
    return sorted(out, key=lambda f: (f.path, f.line))


def _check_function_pair(mod, fn, pmap, pair: str,
                         sites: List[_Site]) -> List[Finding]:
    out: List[Finding] = []
    acquires = [s for s in sites if s.kind == "acquire"]
    releases = [s for s in sites if s.kind == "release"]
    meta = {
        "pair": pair,
        "acquire_sites": [s.line for s in acquires],
        "release_sites": [s.line for s in releases],
    }

    def owned(site: _Site) -> bool:
        ann = _line_annotation(mod, site.line, _OWNS_RE)
        return ann is not None and (ann == pair or ann == "*")

    for acq in acquires:
        if owned(acq) or _escapes(acq, fn, pmap):
            continue
        if not releases:
            out.append(Finding(
                "RT011", mod.rel, acq.line,
                f"resource leak: {acq.recv}.{acq.call.func.attr}() "
                f"({pair}) acquired in {fn.name!r} but no matching "
                f"release ({'/'.join(_releases_of(pair))}) on any path — "
                "release it, hand ownership off explicitly, or annotate "
                f"the transfer with # rt-owns: {pair}",
                meta=dict(meta, kind="leak")))
            continue
        # Release exists: both exits must reach one.  A with-statement
        # around the acquire is managed cleanup; a finally/except release
        # covers the raise path.
        managed = bool(_enclosing(acq.call, pmap, fn, (ast.With,
                                                       ast.AsyncWith)))
        protected = any(_is_protected_release(r, pmap, fn)
                        for r in releases)
        if managed or protected:
            continue
        # Anything between the acquire and the last release that can
        # raise strands the resource.
        last_rel = max(r.line for r in releases)
        risky = None
        for node in walk_own_body(fn):
            if isinstance(node, (ast.Call, ast.Raise)) \
                    and acq.line < getattr(node, "lineno", 0) < last_rel \
                    and node is not acq.call \
                    and all(node is not r.call for r in releases):
                risky = node
                break
        if risky is not None:
            out.append(Finding(
                "RT011", mod.rel, acq.line,
                f"exception-path leak: {acq.recv}."
                f"{acq.call.func.attr}() ({pair}) in {fn.name!r} is "
                f"released only on the fall-through path (line "
                f"{last_rel}); a raise in between (e.g. line "
                f"{risky.lineno}) strands it — move the release into a "
                "finally/with, release in the except handler, or "
                f"annotate a transfer with # rt-owns: {pair}",
                meta=dict(meta, kind="exception_path",
                          risky_line=risky.lineno)))

    # Double release: same subject, same statement block, no intervening
    # acquire or rebind.
    by_block: Dict[int, List[_Site]] = {}
    for r in releases:
        stmt = _stmt_of(r.call, pmap)
        block = pmap.get(stmt)
        by_block.setdefault(id(block), []).append(r)
    for rs in by_block.values():
        by_subject: Dict[str, List[_Site]] = {}
        for r in rs:
            subj = _first_arg_name(r.call)
            if subj:
                by_subject.setdefault(subj, []).append(r)
        for subj, group in by_subject.items():
            if len(group) < 2:
                continue
            group.sort(key=lambda s: s.line)
            first, second = group[0], group[1]
            rebound = any(
                isinstance(n, ast.Assign)
                and first.line < n.lineno < second.line
                and any(dotted_name(t) == subj for t in n.targets)
                for n in walk_own_body(fn))
            reacquired = any(a.line > first.line and a.line < second.line
                             for a in acquires)
            if not rebound and not reacquired \
                    and not owned(second):
                out.append(Finding(
                    "RT011", mod.rel, second.line,
                    f"double release: {subj!r} ({pair}) released at line "
                    f"{first.line} and again here with no re-acquire or "
                    "rebind in between — the second release corrupts the "
                    "pool's refcounts",
                    meta=dict(meta, kind="double_release", subject=subj)))

    # Release of a name this function never bound (and that isn't a
    # parameter or an attribute path): nothing to release.
    params = {a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)
              + list(fn.args.kwonlyargs)}
    if fn.args.vararg:
        params.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        params.add(fn.args.kwarg.arg)
    assigned: Set[str] = set(params)
    for node in walk_own_body(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            assigned.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                assigned.add((alias.asname or alias.name).split(".")[0])
    for r in releases:
        subj = _first_arg_name(r.call)
        if subj is None or "." in subj:
            continue
        if subj not in assigned and not owned(r):
            out.append(Finding(
                "RT011", mod.rel, r.line,
                f"release without acquire: {r.recv}."
                f"{r.call.func.attr}({subj}) in {fn.name!r} releases a "
                "name this function never bound — stale refactor or "
                "typo'd subject",
                meta=dict(meta, kind="release_without_acquire",
                          subject=subj)))
    return out


def _releases_of(pair: str) -> Tuple[str, ...]:
    for name, _acq, rel, _h in _RT_RESOURCE_PAIRS:
        if name == pair:
            return rel
    return ()
