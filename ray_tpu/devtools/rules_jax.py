"""RT010: JAX hot-path compile/sync hazards.

The reference stack catches these classes of bug with profiling after the
fact (a recompile shows as a multi-second step-time spike, a stray host
sync as a flat device-utilization valley).  rtlint finds them at lint
time instead, on the same interprocedural substrate the concurrency
rules use (:class:`~.astutil.ConcurrencyModel`):

**Hot set.**  Seeded at jit boundaries — ``@jax.jit`` /
``@partial(jax.jit, ...)`` defs and ``x = jax.jit(f)`` bindings — and
grown along the call graph: a function is *hot* when it invokes a jitted
program from inside a loop, or is itself invoked from a loop of a hot
function (the engine's ``_loop`` -> ``_run_step`` -> ``_prefill`` chain,
a learner's minibatch epochs).  Per-step code is exactly where a hidden
recompile or sync multiplies by the step count.

Findings:

- **jit-in-loop** — a ``jax.jit(...)`` wrapping (or jit-decorated def)
  lexically inside a loop: every iteration builds a fresh callable with
  an empty cache, i.e. a guaranteed recompile per iteration.
- **unhashable static arg** — a list/dict/set literal passed in a
  ``static_argnums`` position: static args key the compile cache by
  hash, so this raises (or, wrapped, retraces) on every call.
- **host sync in the hot set** — ``.item()`` / ``float()`` / ``int()`` /
  ``bool()`` / ``np.asarray()`` / ``jax.device_get()`` /
  ``.block_until_ready()`` applied to a value reachable from a jitted
  program's output inside a hot function.  Each one blocks the host on
  device completion mid-step; sanctioned syncs (THE step's readback
  point) carry a trailing ``# rt-sync-ok: <reason>``.
- **donated arg read after call** — a ``donate_argnums`` argument is
  dead the moment the call dispatches; a later read on the same path
  sees an invalidated buffer.  Rebinding the name in the donating call's
  own assignment (``self.pools = step(..., self.pools, ...)``) is the
  sanctioned shape.

``--json`` meta carries the hot-path derivation (``hot_via``) so a
finding explains WHY that function is step-rate code.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .astutil import (ConcurrencyModel, FuncInfo, call_name, dotted_name,
                      parent_map, walk_own_body, _line_annotation)
from .rtlint import Finding, Project

_SYNC_OK_RE = re.compile(r"#\s*rt-sync-ok:\s*(.+?)\s*$")

_JIT_NAMES = ("jax.jit", "jit")
_PARTIAL_NAMES = ("partial", "functools.partial")
_LOOPS = (ast.For, ast.While, ast.AsyncFor)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: host-sync builtins taking the syncing value as first argument.
_SYNC_CALLS = frozenset({"float", "int", "bool"})
#: dotted callables that materialize device values on the host.
_SYNC_DOTTED_TAILS = frozenset({"asarray", "array", "device_get"})
_SYNC_DOTTED_RECV = frozenset({"np", "numpy", "jax", "onp"})
#: method calls on a device value that force a sync.
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})


def _full_model(project: Project) -> ConcurrencyModel:
    """Whole-tree model (the concurrency rules scope to core/; the jit
    hot paths live in models/, serve/, rllib/, train/)."""
    cached = getattr(project, "_rt_full_model", None)
    if cached is None:
        cached = project._rt_full_model = ConcurrencyModel(
            list(project.modules))
    return cached


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    """Literal ints inside a static_argnums/donate_argnums value."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


class _JitInfo:
    __slots__ = ("name", "module", "line", "static", "donate")

    def __init__(self, name, module, line, static=(), donate=()):
        self.name = name
        self.module = module  # rel
        self.line = line
        self.static = static
        self.donate = donate


class _JitIndex:
    """Where the jitted callables are: decorated defs (by bare name),
    ``x = jax.jit(f)`` bindings (by scope), ``self.x = jax.jit(f)``
    class attrs (by (module, class))."""

    def __init__(self, project: Project):
        self.defs: Dict[str, _JitInfo] = {}
        self.scoped: Dict[Tuple[str, Optional[int]], Dict[str, _JitInfo]] = {}
        self.class_attrs: Dict[Tuple[str, str], Dict[str, _JitInfo]] = {}
        self.jit_calls: List[Tuple] = []  # (module, Call, parents)
        for mod in project.modules:
            parents = parent_map(mod.tree)
            for node in ast.walk(mod.tree):
                if isinstance(node, _FUNC_NODES):
                    info = self._decorated(node, mod)
                    if info is not None:
                        self.defs.setdefault(node.name, info)
                elif isinstance(node, ast.Call) \
                        and call_name(node) in _JIT_NAMES:
                    self.jit_calls.append((mod, node, parents))
                    self._bind(mod, node, parents)

    def _decorated(self, node, mod) -> Optional[_JitInfo]:
        for dec in node.decorator_list:
            if dotted_name(dec) in _JIT_NAMES:
                return _JitInfo(node.name, mod.rel, node.lineno)
            if isinstance(dec, ast.Call):
                callee = dotted_name(dec.func)
                if callee in _JIT_NAMES:
                    return _JitInfo(node.name, mod.rel, node.lineno,
                                    *self._nums(dec))
                if callee in _PARTIAL_NAMES and dec.args \
                        and dotted_name(dec.args[0]) in _JIT_NAMES:
                    return _JitInfo(node.name, mod.rel, node.lineno,
                                    *self._nums(dec))
        return None

    @staticmethod
    def _nums(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        static = donate = ()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static = _int_tuple(kw.value)
            elif kw.arg == "donate_argnums":
                donate = _int_tuple(kw.value)
        return static, donate

    def _bind(self, mod, call: ast.Call, parents) -> None:
        parent = parents.get(call)
        if not isinstance(parent, ast.Assign) or len(parent.targets) != 1:
            return
        static, donate = self._nums(call)
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            # Scope key: the innermost enclosing function def (by lineno)
            # or None for module scope.
            fn = None
            cur = parents.get(parent)
            while cur is not None:
                if isinstance(cur, _FUNC_NODES):
                    fn = cur
                    break
                cur = parents.get(cur)
            key = (mod.rel, fn.lineno if fn is not None else None)
            self.scoped.setdefault(key, {})[t.id] = _JitInfo(
                t.id, mod.rel, call.lineno, static, donate)
        elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            cls = None
            cur = parents.get(parent)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    cls = cur.name
                    break
                cur = parents.get(cur)
            if cls is not None:
                self.class_attrs.setdefault((mod.rel, cls), {})[t.attr] = \
                    _JitInfo(t.attr, mod.rel, call.lineno, static, donate)

    def resolve_call(self, call: ast.Call, func: FuncInfo
                     ) -> Optional[_JitInfo]:
        """Is this call site invoking a jitted callable?"""
        f = call.func
        if isinstance(f, ast.Name):
            # Innermost scope first: function-local binding, then
            # enclosing defs, then module scope, then jitted-def names.
            cur = func
            while cur is not None:
                hit = self.scoped.get(
                    (func.module.rel, cur.node.lineno), {}).get(f.id)
                if hit is not None:
                    return hit
                cur = cur.parent
            hit = self.scoped.get((func.module.rel, None), {}).get(f.id)
            if hit is not None:
                return hit
            return self.defs.get(f.id)
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and func.cls is not None:
                hit = self.class_attrs.get(
                    (func.module.rel, func.cls), {}).get(f.attr)
                if hit is not None:
                    return hit
            # paged.paged_decode_step(...) / models.adapter_load(...)
            return self.defs.get(f.attr)
        return None


def _in_loop(node: ast.AST, func_node: ast.AST, parents) -> bool:
    """Is ``node`` lexically inside a loop within this function?"""
    cur = parents.get(node)
    while cur is not None and cur is not func_node:
        if isinstance(cur, _LOOPS):
            return True
        if isinstance(cur, _FUNC_NODES):
            return False
        cur = parents.get(cur)
    return False


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _self_attrs_in(node: ast.AST) -> Set[str]:
    return {n.attr for n in ast.walk(node)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name) and n.value.id == "self"}


def _taint_targets(target: ast.AST, names: Set[str], attrs: Set[str]):
    if isinstance(target, ast.Name):
        names.add(target.id)
    elif isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        attrs.add(target.attr)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            _taint_targets(el, names, attrs)


def _refs_taint(node: ast.AST, names: Set[str], attrs: Set[str]) -> bool:
    if _names_in(node) & names:
        return True
    return bool(_self_attrs_in(node) & attrs)


def _derives_taint(node: ast.AST, names: Set[str], attrs: Set[str],
                   jit_calls: Set[int]) -> bool:
    """Does evaluating ``node`` yield device data?  A jit call does; so
    does any pure access path over tainted values (name, subscript,
    attribute, method call ON a tainted receiver like ``aux.items()``).
    A call to anything ELSE launders the taint: a host function's return
    is host data (``env.step(acts)`` does not make rewards device
    arrays)."""
    has_jit = any(id(c) in jit_calls for c in ast.walk(node)
                  if isinstance(c, ast.Call))
    if has_jit:
        return True
    if not _refs_taint(node, names, attrs):
        return False
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        if isinstance(f, ast.Attribute) \
                and _refs_taint(f.value, names, attrs):
            continue  # method on a tainted receiver keeps the taint
        return False
    return True


class _HotFunc:
    __slots__ = ("func", "via", "jit_sites", "whole_body_hot")

    def __init__(self, func, via, whole_body_hot):
        self.func = func
        self.via = via  # human-readable derivation
        self.jit_sites: List[Tuple[ast.Call, _JitInfo]] = []
        # True when the WHOLE function runs per step (it is invoked from
        # a loop).  False when it merely CONTAINS the step loop: its
        # post-loop epilogue runs once, and a sync there is the
        # sanctioned readback point, not a per-step stall.
        self.whole_body_hot = whole_body_hot


def _hot_set(model: ConcurrencyModel, index: _JitIndex
             ) -> Dict[FuncInfo, _HotFunc]:
    # Per-function: jitted call sites + whether each is inside a loop.
    jit_sites: Dict[FuncInfo, List[Tuple[ast.Call, _JitInfo, bool]]] = {}
    in_loop_edges: Dict[FuncInfo, List[Tuple[FuncInfo, int]]] = {}
    pmaps: Dict[str, dict] = {}
    for func in model.functions:
        pmap = pmaps.setdefault(func.module.rel, parent_map(func.module.tree))
        for node in walk_own_body(func.node):
            if not isinstance(node, ast.Call):
                continue
            loop = _in_loop(node, func.node, pmap)
            ji = index.resolve_call(node, func)
            if ji is not None:
                jit_sites.setdefault(func, []).append((node, ji, loop))
            if loop:
                callee = model._resolve_callable(node.func, func)
                if callee is not None:
                    in_loop_edges.setdefault(callee, []).append(
                        (func, node.lineno))
    hot: Dict[FuncInfo, _HotFunc] = {}

    def mark(func, via, whole_body):
        hf = hot.get(func)
        if hf is not None:
            hf.whole_body_hot = hf.whole_body_hot or whole_body
            return
        hf = _HotFunc(func, via, whole_body)
        hf.jit_sites = [(c, j) for c, j, _ in jit_sites.get(func, [])]
        hot[func] = hf

    # Seed A: loops directly driving a jitted program.
    for func, sites in jit_sites.items():
        for call, ji, loop in sites:
            if loop:
                mark(func, f"calls jitted {ji.name!r} in a loop "
                           f"(line {call.lineno})", whole_body=False)
                break
    # Seed B: jit-calling functions themselves driven from a loop.
    for func, sites in jit_sites.items():
        if func not in in_loop_edges:
            continue
        caller, line = in_loop_edges[func][0]
        mark(func, f"calls jitted {sites[0][1].name!r}; invoked from a "
                   f"loop in {caller.qualname} (line {line})",
             whole_body=True)
    # One propagation round: functions a hot function drives from ITS
    # loops (the engine's _run_step -> _prefill), and jit-calling
    # functions a hot function calls at all (per-step helpers).
    for func in list(hot):
        for callee, edges in in_loop_edges.items():
            if callee in hot:
                continue
            for caller, line in edges:
                if caller in hot:
                    mark(callee, f"invoked from a loop in hot "
                                 f"{caller.qualname} (line {line})",
                         whole_body=True)
                    break
    for cs in model.call_sites:
        if cs.func in hot and cs.callee not in hot \
                and cs.callee in jit_sites:
            mark(cs.callee,
                 f"calls a jitted program; called from hot "
                 f"{cs.func.qualname} (line {cs.line})", whole_body=True)
    return hot


def _function_taint(hf: _HotFunc) -> Tuple[Set[str], Set[str]]:
    """Names/self-attrs holding (or derived from) jitted-program outputs
    inside one hot function."""
    func = hf.func
    jit_calls = {id(c) for c, _ in hf.jit_sites}
    names: Set[str] = set()
    attrs: Set[str] = set()
    stmts = [n for n in walk_own_body(func.node)]
    for _ in range(3):  # seed + two derivation rounds
        before = (len(names), len(attrs))
        for node in stmts:
            if isinstance(node, ast.Assign):
                if _derives_taint(node.value, names, attrs, jit_calls):
                    for t in node.targets:
                        _taint_targets(t, names, attrs)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _derives_taint(node.iter, names, attrs, jit_calls):
                    _taint_targets(node.target, names, attrs)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if _derives_taint(gen.iter, names, attrs, jit_calls):
                        _taint_targets(gen.target, names, attrs)
        if (len(names), len(attrs)) == before:
            break
    return names, attrs


def _sync_kind(call: ast.Call, names: Set[str], attrs: Set[str]
               ) -> Optional[str]:
    """The sync shape of a call on tainted data, or None."""
    f = call.func
    if isinstance(f, ast.Name) and f.id in _SYNC_CALLS and call.args:
        if _refs_taint(call.args[0], names, attrs):
            return f"{f.id}() on a device value"
        return None
    if isinstance(f, ast.Attribute):
        if f.attr in _SYNC_METHODS \
                and _refs_taint(f.value, names, attrs):
            return f".{f.attr}() on a device value"
        if f.attr in _SYNC_DOTTED_TAILS and call.args:
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id in _SYNC_DOTTED_RECV \
                    and _refs_taint(call.args[0], names, attrs):
                return f"{recv.id}.{f.attr}() on a device value"
    return None


def check_rt010(project: Project) -> List[Finding]:
    model = _full_model(project)
    index = _JitIndex(project)
    out: List[Finding] = []

    # -- jit-in-loop + unhashable static args (whole tree) --------------------
    for mod, call, parents in index.jit_calls:
        if _in_loop(call, mod.tree, parents):
            out.append(Finding(
                "RT010", mod.rel, call.lineno,
                "jax.jit(...) inside a loop: each iteration builds a "
                "fresh callable with an empty compile cache (a recompile "
                "per iteration) — hoist the jitted callable out of the "
                "loop",
                meta={"kind": "jit_in_loop"}))
    for mod in project.modules:
        pmap = parent_map(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, _FUNC_NODES):
                info = index._decorated(node, mod)
                if info is not None and _in_loop(node, mod.tree, pmap):
                    out.append(Finding(
                        "RT010", mod.rel, node.lineno,
                        f"jitted def {node.name!r} defined inside a loop: "
                        "every iteration re-wraps it with an empty "
                        "compile cache — define it once outside the loop",
                        meta={"kind": "jit_in_loop"}))

    hot = _hot_set(model, index)
    for hf in hot.values():
        func = hf.func
        mod = func.module
        names, attrs = _function_taint(hf)
        for call, ji in hf.jit_sites:
            # Unhashable static args: compile-cache keys must hash.
            for idx in ji.static:
                if idx < len(call.args) and isinstance(
                        call.args[idx], (ast.List, ast.Dict, ast.Set)):
                    out.append(Finding(
                        "RT010", mod.rel, call.lineno,
                        f"unhashable literal in static_argnums position "
                        f"{idx} of jitted {ji.name!r}: static args key "
                        "the compile cache by hash — pass a tuple or a "
                        "hashable config object",
                        meta={"kind": "unhashable_static",
                              "program": ji.name, "argnum": idx,
                              "hot_via": hf.via}))
            out.extend(_check_donation(func, call, ji, hf))
        if not names and not attrs:
            continue
        pmap = parent_map(func.node)
        for node in walk_own_body(func.node):
            if not isinstance(node, ast.Call):
                continue
            kind = _sync_kind(node, names, attrs)
            if kind is None:
                continue
            if not hf.whole_body_hot \
                    and not _in_loop(node, func.node, pmap):
                # The function CONTAINS the step loop; its epilogue runs
                # once — a post-loop readback is the sanctioned shape.
                continue
            if _line_annotation(mod, node.lineno, _SYNC_OK_RE):
                continue
            out.append(Finding(
                "RT010", mod.rel, node.lineno,
                f"implicit host sync in the jit hot set: {kind} inside "
                f"{func.qualname} ({hf.via}) blocks the host on device "
                "completion every step — hoist the readback out of the "
                "hot path or vet THE step's readback point with "
                "# rt-sync-ok: <reason>",
                meta={"kind": "host_sync", "sync": kind,
                      "hot_via": hf.via}))
    return _dedup(out)


def _check_donation(func: FuncInfo, call: ast.Call, ji: _JitInfo,
                    hf: _HotFunc) -> List[Finding]:
    """A donated buffer is dead after the call: flag loads of the donated
    name in subsequent statements of the same block, unless the donating
    call's own assignment (or a later one) rebinds it first."""
    if not ji.donate:
        return []
    donated: List[Tuple[str, Optional[str]]] = []  # (name, self_attr)
    for idx in ji.donate:
        if idx >= len(call.args):
            continue
        arg = call.args[idx]
        if isinstance(arg, ast.Name):
            donated.append((arg.id, None))
        elif isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id == "self":
            donated.append((arg.attr, "self"))
    if not donated:
        return []
    pmap = parent_map(func.node)
    # The statement containing the call, and its containing block.
    stmt = call
    while stmt in pmap and not isinstance(stmt, ast.stmt):
        stmt = pmap[stmt]
    block = pmap.get(stmt)
    if block is None:
        return []
    for field in ("body", "orelse", "finalbody"):
        stmts = getattr(block, field, None)
        if stmts and stmt in stmts:
            break
    else:
        return []
    # Names the donating statement itself rebinds.
    rebound: Set[Tuple[str, Optional[str]]] = set()
    if isinstance(stmt, ast.Assign):
        rn: Set[str] = set()
        ra: Set[str] = set()
        for t in stmt.targets:
            _taint_targets(t, rn, ra)
        rebound |= {(n, None) for n in rn} | {(a, "self") for a in ra}
    out: List[Finding] = []
    live = [d for d in donated if d not in rebound]
    for later in stmts[stmts.index(stmt) + 1:]:
        if not live:
            break
        # Loads are checked BEFORE this statement's rebinds take effect:
        # in ``buf = buf + 0`` the RHS still reads the dead buffer.
        for name, recv in list(live):
            for node in ast.walk(later):
                if recv is None and isinstance(node, ast.Name) \
                        and node.id == name \
                        and isinstance(node.ctx, ast.Load):
                    hit = node
                elif recv == "self" and isinstance(node, ast.Attribute) \
                        and node.attr == name \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and isinstance(node.ctx, ast.Load):
                    hit = node
                else:
                    continue
                label = f"self.{name}" if recv else name
                out.append(Finding(
                    "RT010", func.module.rel, hit.lineno,
                    f"donated argument {label!r} read after the donating "
                    f"call to jitted {ji.name!r} (line {call.lineno}): "
                    "donate_argnums invalidates the buffer at dispatch — "
                    "rebind the name from the call's result before any "
                    "further use",
                    meta={"kind": "donation_use_after", "program": ji.name,
                          "donated": label, "call_line": call.lineno,
                          "hot_via": hf.via}))
                live = [d for d in live if d != (name, recv)]
                break
        for node in ast.walk(later):
            if isinstance(node, ast.Assign):
                rn, ra = set(), set()
                for t in node.targets:
                    _taint_targets(t, rn, ra)
                live = [d for d in live
                        if d not in {(n, None) for n in rn}
                        and d not in {(a, "self") for a in ra}]
    return out


def _dedup(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line))
