"""RT003: RPC-surface drift.

The reference gets wire safety from 22 protobuf files — every RPC has one
typed schema shared by caller and callee, and a rename breaks the build.
This framework ships msgpack dicts, so the three legs of each method
(client call string, ``h_*`` handler, ``schema.REQUIRED`` row) can drift
apart silently.  RT003 reconciles them statically:

- every method the package calls must have an ``h_<method>`` handler in
  ``core/head.py``, ``core/node_main.py``, or ``core/worker_main.py``
  (the worker-plane peer servers — direct actor calls, leased task
  submission, direct-result streaming — are RPC surface like any other);
- every method ``core/client.py`` sends that can mutate head state (i.e.
  is not in its ``IDEMPOTENT_METHODS`` read set) must have a
  ``schema.REQUIRED`` row so the boundary validates it;
- no orphan schema rows (row without a handler);
- no orphan handlers (handler no code calls — dead wire surface).

Handlers on node/worker servers register outside the head's ``_validated``
wrapper, so they must validate their schema rows in-handler (mirroring
``pull_object``/``read_log``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .astutil import const_str, iter_functions, str_collection_literal
from .rtlint import Finding, Project

#: call wrappers whose FIRST argument is the wire method name.
CALL_WRAPPERS = {
    "call", "call_bg", "call_batched", "call_async", "_call", "_call_bg_raw",
}
#: call wrappers whose SECOND argument is the method (first is an address).
ADDRESSED_WRAPPERS = {"_node_call"}


def _called_methods(module) -> Dict[str, int]:
    """method name -> first call-site line in this module."""
    out: Dict[str, int] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.attr if isinstance(f, ast.Attribute)
                 else f.id if isinstance(f, ast.Name) else None)
        method = None
        if fname in CALL_WRAPPERS and node.args:
            method = const_str(node.args[0])
        elif fname in ADDRESSED_WRAPPERS and len(node.args) >= 2:
            method = const_str(node.args[1])
        if method is not None:
            out.setdefault(method, node.lineno)
    return out


def _handlers(module) -> Dict[str, int]:
    return {
        fn.name[2:]: fn.lineno
        for fn in iter_functions(module.tree)
        if fn.name.startswith("h_")
    }


def check_rt003(project: Project) -> List[Finding]:
    client = project.find("core/client.py")
    head = project.find("core/head.py")
    node = project.find("core/node_main.py")
    worker = project.find("core/worker_main.py")
    schema = project.find("core/schema.py")
    if client is None or head is None or schema is None:
        return []  # not a control-plane tree (synthetic single-rule runs)
    out: List[Finding] = []

    handlers: Dict[str, Tuple[str, int]] = {}
    for mod in (m for m in (head, node, worker) if m is not None):
        for name, line in _handlers(mod).items():
            handlers.setdefault(name, (mod.rel, line))

    all_calls: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules:
        for method, line in _called_methods(mod).items():
            all_calls.setdefault(method, (mod.rel, line))

    idempotent: Set[str] = set(
        str_collection_literal(client.tree, "IDEMPOTENT_METHODS") or ()
    )
    schema_rows: Dict[str, int] = {}
    for stmt in schema.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if any(isinstance(t, ast.Name) and t.id == "REQUIRED"
                   for t in targets) and isinstance(stmt.value, ast.Dict):
                for k in stmt.value.keys:
                    s = const_str(k)
                    if s is not None:
                        schema_rows[s] = k.lineno

    # Leg 1: every called method has a handler.
    for method, (rel, line) in sorted(all_calls.items()):
        if method not in handlers:
            out.append(Finding(
                "RT003", rel, line,
                f"RPC {method!r} is called but no h_{method} handler "
                "exists in core/head.py, core/node_main.py, or "
                "core/worker_main.py",
            ))

    # Leg 2: every mutating method the PACKAGE sends carries a schema row
    # (not just core/client.py's — scripts.py, worker_main.py, the metric
    # flusher and daemons speak the same wire and drift the same way).
    # Methods without a handler are already leg-1 findings; skip them here.
    for method, (rel, line) in sorted(all_calls.items()):
        if method in idempotent or method in schema_rows \
                or method not in handlers:
            continue
        out.append(Finding(
            "RT003", rel, line,
            f"mutating RPC {method!r} has no schema.REQUIRED row — the "
            "head boundary can't validate it (add the row, or add the "
            "method to IDEMPOTENT_METHODS if it is a pure read)",
        ))

    # Leg 3: no orphan schema rows.
    for method, line in sorted(schema_rows.items()):
        if method not in handlers:
            out.append(Finding(
                "RT003", schema.rel, line,
                f"schema.REQUIRED row {method!r} has no h_{method} "
                "handler — dead schema surface",
            ))

    # Leg 4: no orphan handlers (dead wire surface nothing can reach).
    for method, (rel, line) in sorted(handlers.items()):
        if method not in all_calls:
            out.append(Finding(
                "RT003", rel, line,
                f"handler h_{method} has no call site anywhere in the "
                "package — dead wire surface (remove it, or wire the "
                "caller that should use it)",
            ))
    return out
