"""Runtime concurrency sentinel: instrumented locks for the control plane.

The dynamic complement to rtlint's RT002 (the C++ reference leans on TSAN
in CI for this).  ``core/`` creates its locks through :func:`make_lock` /
:func:`make_rlock`:

- **disabled** (default): returns a plain ``threading.Lock``/``RLock`` —
  the zero-overhead path, nothing is wrapped.
- **enabled** (``RT_DEBUG_LOCKS=1``): returns a :class:`SentinelLock` that
  records each thread's acquisition order, asserts one consistent GLOBAL
  ordering between lock name-classes (acquiring B while holding A after
  some thread ever acquired A while holding B raises
  :class:`LockOrderError` — the textbook ABBA deadlock, caught on the
  first inverted acquisition instead of the first lost race), detects
  same-instance re-entry on non-reentrant locks, and logs any lock held
  longer than ``RT_DEBUG_LOCKS_HOLD_S`` (default 1.0s — a held lock that
  long under a 0.2s control-plane tick is a stall in waiting).
- **race sentinel** (``RT_DEBUG_LOCKS=2``, implies level 1): classes
  decorated with :func:`guarded` enforce their declared guard map at
  runtime — every rebind of a field listed in ``_RT_GUARDED_BY`` asserts
  the named lock is held by the writing thread, else
  :class:`GuardViolation` names the class, field, guard, and thread.
  The same maps are what rtlint RT007 verifies statically; this is the
  dynamic twin (the role TSAN + ``GUARDED_BY`` annotations play in the
  C++ reference), soaked by ``scripts/chaos_soak.sh`` under
  ``RT_DEBUG_LOCKS=2``.  ``__init__`` is exempt (the object is not yet
  published); container mutation without a rebind is invisible to
  ``__setattr__`` — the swap idiom (``x, self._x = self._x, []``) the
  hot paths use is exactly what gets checked.

Ordering is tracked between lock *names* (one name per call site /
role, e.g. ``client.put_batch``), not instances: every ``Client`` has its
own ``_put_batch_lock`` but the safe order between the *roles* must be
globally consistent.  Same-name edges between different instances are
skipped — instances of one role are unordered peers.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.locks")

ENV_FLAG = "RT_DEBUG_LOCKS"
ENV_HOLD = "RT_DEBUG_LOCKS_HOLD_S"


class LockOrderError(RuntimeError):
    """Two lock name-classes were acquired in both orders — an ABBA
    deadlock waiting for the right thread interleaving."""


class GuardViolation(RuntimeError):
    """A field declared guarded (``_RT_GUARDED_BY``) was rebound by a
    thread that does not hold its guard lock — a data race, caught at the
    racing write instead of at the corrupted read."""


def level() -> int:
    """Sentinel level: 0 off, 1 ordering checks, 2 + guard-map races."""
    raw = os.environ.get(ENV_FLAG, "")
    if raw in ("1", "2"):
        return int(raw)
    return 0


def enabled() -> bool:
    return level() >= 1


def race_sentinel_enabled() -> bool:
    return level() >= 2


def _hold_threshold() -> float:
    try:
        return float(os.environ.get(ENV_HOLD, "1.0"))
    except ValueError:
        return 1.0


# Global ordering state: (first_name, then_name) -> where first observed.
# RLock, deliberately: dict inserts under it can allocate and trigger
# cyclic GC, and ObjectRef.__del__ acquires a SentinelLock (_free_lock)
# whose order check re-enters here on the SAME thread — a plain Lock
# would self-deadlock the debug run (the exact GC-reentrancy hazard
# core/object_ref.py documents for client locks).
_edges: Dict[Tuple[str, str], str] = {}
_edges_lock = threading.RLock()
_held = threading.local()  # per-thread stack of (SentinelLock, t_acquire)


def reset_sentinel_state() -> None:
    """Forget every observed ordering edge (tests)."""
    with _edges_lock:
        _edges.clear()


def _held_stack() -> List[tuple]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _order_path(src: str, dst: str) -> Optional[List[str]]:
    """BFS over recorded edges: the established acquisition chain
    ``src -> ... -> dst`` if one exists.  A GLOBAL ordering is consistent
    only if no such chain is ever inverted — checking just the direct edge
    would miss 3+-lock cycles (A->B, B->C, then C-while-holding... A)."""
    with _edges_lock:
        adj: Dict[str, List[str]] = {}
        for a, b in _edges:
            adj.setdefault(a, []).append(b)
        prev: Dict[str, Optional[str]] = {src: None}
        queue = [src]
        while queue:
            cur = queue.pop(0)
            if cur == dst:
                path = []
                node: Optional[str] = cur
                while node is not None:
                    path.append(node)
                    node = prev[node]
                return list(reversed(path))
            for nxt in adj.get(cur, ()):
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
    return None


def _site() -> str:
    """The caller's frame OUTSIDE this module — the acquire/release site an
    operator can actually go look at (wrapper-internal frames vary with the
    entry path: acquire() vs the ``with`` protocol)."""
    for f in reversed(traceback.extract_stack()):
        if f.filename != __file__:
            return f"{f.filename}:{f.lineno} in {f.name}"
    return "<unknown>"


class SentinelLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper with ordering checks."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    # -- checks ----------------------------------------------------------------

    def _check_order(self) -> None:
        """Raise BEFORE a blocking acquire that inverts an established
        order — the whole point is to fail loudly instead of deadlocking."""
        me = threading.current_thread().name
        for other, _ in _held_stack():
            if other is self:
                if not self.reentrant:
                    raise LockOrderError(
                        f"thread {me!r} re-acquiring non-reentrant lock "
                        f"{self.name!r} it already holds — guaranteed "
                        f"deadlock (at {_site()})"
                    )
                continue
            if other.name == self.name:
                continue  # peer instances of one role: unordered
            path = _order_path(self.name, other.name)
            if path is not None:
                with _edges_lock:
                    first_seen = _edges.get((path[0], path[1]), "<unknown>")
                raise LockOrderError(
                    f"lock-order inversion: thread {me!r} acquires "
                    f"{self.name!r} while holding {other.name!r} (at "
                    f"{_site()}), but the opposite order "
                    f"{' -> '.join(repr(p) for p in path)} is established "
                    f"(first edge recorded at {first_seen}) — "
                    f"{'ABBA' if len(path) == 2 else 'cyclic'} deadlock"
                )

    def _record_edges(self) -> None:
        """Register held -> self ordering edges.  Called only after a
        SUCCESSFUL blocking acquire: a failed (or try-lock) attempt imposed
        no ordering, and try-lock-with-back-off is a legitimate
        deadlock-avoidance idiom that must not poison the edge table."""
        site = None
        for other, _ in _held_stack():
            if other is self or other.name == self.name:
                continue
            if site is None:
                site = _site()
            with _edges_lock:
                _edges.setdefault((other.name, self.name), site)

    def _on_acquired(self) -> None:
        _held_stack().append((self, time.monotonic()))

    def _on_release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                _, t0 = stack.pop(i)
                dt = time.monotonic() - t0
                if dt > _hold_threshold():
                    logger.warning(
                        "lock %r held %.3fs (> %.3fs threshold) — "
                        "released at %s",
                        self.name, dt, _hold_threshold(), _site(),
                    )
                return

    # -- lock protocol ---------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._check_order()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if blocking:
                self._record_edges()
            self._on_acquired()
        return ok

    def release(self):
        self._on_release()
        self._lock.release()

    def locked(self):
        locked = getattr(self._lock, "locked", None)
        return locked() if locked is not None else False

    def held_by_current_thread(self) -> bool:
        return any(other is self for other, _ in _held_stack())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SentinelLock {self.name!r} reentrant={self.reentrant}>"


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented when ``RT_DEBUG_LOCKS=1``."""
    if not enabled():
        return threading.Lock()
    return SentinelLock(name)


def make_rlock(name: str):
    """A ``threading.RLock`` — instrumented when ``RT_DEBUG_LOCKS=1``."""
    if not enabled():
        return threading.RLock()
    return SentinelLock(name, reentrant=True)


# -- guard-map race sentinel (RT_DEBUG_LOCKS=2) --------------------------------


def guarded(cls):
    """Class decorator enforcing the class's declared guard map at runtime.

    The map is the class attribute ``_RT_GUARDED_BY = {"field":
    "_lock_attr", ...}`` — the same declaration rtlint RT007 verifies
    statically.  Under ``RT_DEBUG_LOCKS=2`` every attribute REBIND of a
    listed field asserts the instance's guard lock is held by the current
    thread (``__init__`` exempt: the object is unpublished while it
    constructs).  Any other level returns the class untouched — the
    disabled path adds zero wrappers, zero per-write cost.
    """
    guards = getattr(cls, "_RT_GUARDED_BY", None)
    if not race_sentinel_enabled() or not guards:
        return cls

    orig_init = cls.__init__
    orig_setattr = cls.__setattr__

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        object.__setattr__(self, "_rt_guards_armed", True)

    def __setattr__(self, name, value):
        lock_attr = guards.get(name)
        if lock_attr is not None \
                and getattr(self, "_rt_guards_armed", False):
            lock = getattr(self, lock_attr, None)
            if isinstance(lock, SentinelLock) \
                    and not lock.held_by_current_thread():
                raise GuardViolation(
                    f"guarded field {cls.__name__}.{name} rebound by "
                    f"thread {threading.current_thread().name!r} without "
                    f"holding its guard {lock.name!r} ({lock_attr}) — "
                    f"declared in {cls.__name__}._RT_GUARDED_BY; racing "
                    f"write at {_site()}"
                )
        orig_setattr(self, name, value)

    cls.__init__ = __init__
    cls.__setattr__ = __setattr__
    return cls
