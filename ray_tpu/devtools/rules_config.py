"""RT009: spawn-env contract drift.

The spawner half of the framework hands state to child processes through
``RT_*`` environment variables (head -> node daemon -> worker), and the
reader half picks them up with raw ``os.environ`` reads scattered across
``cluster_utils.py``, ``node_main.py``, ``api.py``, ``worker_main.py``,
``train/worker_group.py``...  A typo'd key or a renamed-on-one-side-only
variable fails SILENTLY (``environ.get`` default kicks in) — the same
drift class RT003 closes for RPC methods.  ``core/config.py`` therefore
carries ``SPAWN_ENV_CONTRACT``, a catalog of every ad-hoc ``RT_*`` key,
and RT009 reconciles it three ways (mirroring RT003's shape):

- **missing**: an ``RT_*`` key is read outside ``core/config.py`` but has
  no catalog entry;
- **stale**: a catalog entry no module reads — the contract must shrink
  when the reader goes away;
- **orphan write**: an ``RT_*`` key is exported into a spawn environment
  (``os.environ[k] =``, an ``RT_*=...`` keyword, a dict literal key) but
  is neither in the catalog nor a ``Config`` field override — dead env
  plumbing no child ever reads.

Plus the config-shadow leg: reading ``RT_<FIELD>`` ad hoc when ``<field>``
is a ``Config`` dataclass field bypasses ``system_config`` overrides and
type coercion — use ``get_config().<field>``.

Key names resolve through module-level string constants
(``ENV_FLAG = "RT_DEBUG_LOCKS"; os.environ.get(ENV_FLAG)`` counts).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .astutil import const_str, dotted_name
from .rtlint import Finding, Project

CONTRACT_VAR = "SPAWN_ENV_CONTRACT"


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = const_str(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out


def _key_of(node, consts: Dict[str, str]) -> Optional[str]:
    s = const_str(node)
    if s is None and isinstance(node, ast.Name):
        s = consts.get(node.id)
    if s is not None and s.startswith("RT_"):
        return s
    return None


def _environ_reads(module) -> List[Tuple[str, int]]:
    """(key, line) for const-resolvable RT_* environ reads."""
    consts = _module_str_consts(module.tree)
    out: List[Tuple[str, int]] = []
    for node in ast.walk(module.tree):
        key = None
        if isinstance(node, ast.Call):
            f = dotted_name(node.func)
            if f is not None and f.endswith("environ.get") and node.args:
                key = _key_of(node.args[0], consts)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            recv = dotted_name(node.value)
            if recv is not None and recv.endswith("environ"):
                key = _key_of(node.slice, consts)
        if key is not None:
            out.append((key, node.lineno))
    return out


def _environ_writes(module) -> List[Tuple[str, int]]:
    """(key, line) for RT_* spawn-env exports: environ item stores/pops,
    RT_*-named keywords, and RT_* dict-literal keys."""
    consts = _module_str_consts(module.tree)
    out: List[Tuple[str, int]] = []
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    key = _key_of(t.slice, consts)
                    if key is not None:
                        out.append((key, t.lineno))
        elif isinstance(node, ast.Call):
            f = dotted_name(node.func)
            if f is not None and f.endswith("environ.pop") and node.args:
                key = _key_of(node.args[0], consts)
                if key is not None:
                    out.append((key, node.lineno))
            for kw in node.keywords:
                if kw.arg is not None and kw.arg.startswith("RT_"):
                    out.append((kw.arg, node.lineno))
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                key = _key_of(k, consts) if k is not None else None
                if key is not None:
                    out.append((key, k.lineno))
    return out


def _contract(config) -> Optional[Dict[str, int]]:
    """key -> catalog line, from the SPAWN_ENV_CONTRACT dict literal."""
    for stmt in config.tree.body:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target] if isinstance(stmt, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == CONTRACT_VAR
               for t in targets) and isinstance(stmt.value, ast.Dict):
            out: Dict[str, int] = {}
            for k in stmt.value.keys:
                s = const_str(k)
                if s is not None:
                    out[s] = k.lineno
            return out
    return None


def _config_fields(config) -> List[str]:
    for node in ast.walk(config.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [stmt.target.id for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)]
    return []


def check_rt009(project: Project) -> List[Finding]:
    config = project.find("core/config.py")
    if config is None:
        return []  # not a control-plane tree
    contract = _contract(config)
    if contract is None:
        return [Finding(
            "RT009", config.rel, 1,
            f"core/config.py has no {CONTRACT_VAR} dict — the spawn-env "
            "contract catalog is the anchor this rule reconciles against",
            meta={"kind": "no-contract"})]
    overrides = {f"RT_{f.upper()}" for f in _config_fields(config)}
    out: List[Finding] = []
    reads: Dict[str, Tuple[str, int]] = {}
    writes: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules:
        if mod is config:
            continue
        for key, line in _environ_reads(mod):
            reads.setdefault(key, (mod.rel, line))
            if key in overrides:
                field = key[3:].lower()
                out.append(Finding(
                    "RT009", mod.rel, line,
                    f"ad-hoc os.environ read of {key!r} shadows the "
                    f"Config field {field!r} — use get_config().{field} "
                    "(env override, system_config, and type coercion all "
                    "apply there)",
                    meta={"key": key, "kind": "shadow", "field": field}))
            elif key not in contract:
                out.append(Finding(
                    "RT009", mod.rel, line,
                    f"os.environ read of {key!r} has no "
                    f"{CONTRACT_VAR} entry in core/config.py — "
                    "uncataloged spawn-env keys drift silently (add the "
                    "entry, or read it through get_config())",
                    meta={"key": key, "kind": "missing"}))
        for key, line in _environ_writes(mod):
            writes.setdefault(key, (mod.rel, line))
    for key, line in sorted(contract.items()):
        if key not in reads:
            out.append(Finding(
                "RT009", config.rel, line,
            f"{CONTRACT_VAR} entry {key!r} is read nowhere in the "
                "package — stale contract surface, remove the entry "
                "(and any spawner still exporting it)",
                meta={"key": key, "kind": "stale"}))
    for key, (rel, line) in sorted(writes.items()):
        if key in contract or key in overrides:
            continue
        out.append(Finding(
            "RT009", rel, line,
            f"spawn-env export of {key!r} matches no {CONTRACT_VAR} "
            "entry and no Config field — dead env plumbing no child "
            "reads (remove it, or catalog the reader's contract)",
            meta={"key": key, "kind": "orphan-write"}))
    return out
