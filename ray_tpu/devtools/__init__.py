"""Developer tooling for the ray_tpu control plane.

Two complementary halves (the protections the reference gets from its
protobuf schemas + C++ sanitizer CI — reference: src/ray/protobuf/*.proto,
TSAN/ASAN jobs — rebuilt for a msgpack-dict, pure-Python control plane):

- **rtlint** (`python -m ray_tpu lint`, :mod:`ray_tpu.devtools.rtlint`):
  AST-based static analysis that knows this framework's idioms — blocking
  calls inside the head's async handlers, threading locks held across an
  ``await``, client-call/handler/schema drift on the RPC surface, nested
  ``ray_tpu.get`` in remote functions, undaemonized threads, metric-name
  drift.  Rules RT001–RT006; vetted exceptions live in ``ray_tpu/.rtlint-allowlist``.
- **lock sentinel** (:mod:`ray_tpu.devtools.locks`): an opt-in
  (``RT_DEBUG_LOCKS=1``) instrumented lock used by ``core/`` that records
  per-thread acquisition order, asserts one consistent global lock
  ordering, and logs locks held past a threshold — the dynamic complement
  to rule RT002.
"""

from __future__ import annotations


def __getattr__(name):
    # Lazy: importing ray_tpu.devtools.locks from core/ at startup must not
    # drag the whole lint engine in.
    if name in ("run_lint", "Finding", "main"):
        from . import rtlint

        return getattr(rtlint, name)
    if name in ("make_lock", "make_rlock", "LockOrderError"):
        from . import locks

        return getattr(locks, name)
    raise AttributeError(name)
