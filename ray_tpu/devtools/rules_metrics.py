"""RT006: ``ray_tpu_*`` metric-name drift.

Every built-in metric the framework emits must be declared (name and
kind) in the ``BUILTIN_METRICS`` catalog in ``util/metrics.py``.  The
catalog is what operators wire dashboards and alerts against; an emitted
name missing from it is invisible infrastructure, a catalog row nothing
emits is a dashboard panel that will never populate, and one name used
as two kinds renders a Prometheus exposition the scraper rejects.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .astutil import const_str, str_dict_literal
from .rtlint import Finding, Project

#: constructor / memoized-getter name -> metric kind.
EMITTERS = {
    "Counter": "counter", "get_counter": "counter",
    "Gauge": "gauge", "get_gauge": "gauge",
    "Histogram": "histogram", "get_histogram": "histogram",
}
PREFIX = "ray_tpu_"


def _emitted(project: Project) -> Dict[str, List[Tuple[str, int, str]]]:
    """metric name -> [(path, line, kind), ...] across the package."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}
    for module in project.modules:
        if module.rel.endswith("util/metrics.py"):
            continue  # the instrument classes themselves live here
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = (f.attr if isinstance(f, ast.Attribute)
                     else f.id if isinstance(f, ast.Name) else None)
            kind = EMITTERS.get(fname or "")
            if kind is None or not node.args:
                continue
            name = const_str(node.args[0])
            if name is None or not name.startswith(PREFIX):
                continue
            out.setdefault(name, []).append((module.rel, node.lineno, kind))
    return out


def check_rt006(project: Project) -> List[Finding]:
    metrics_mod = project.find("util/metrics.py")
    if metrics_mod is None:
        return []
    out: List[Finding] = []
    catalog = str_dict_literal(metrics_mod.tree, "BUILTIN_METRICS")
    if catalog is None:
        out.append(Finding(
            "RT006", metrics_mod.rel, 1,
            "no BUILTIN_METRICS catalog ({name: kind} dict) — built-in "
            "ray_tpu_* metrics have nothing to validate against",
        ))
        return out
    emitted = _emitted(project)
    for name, sites in sorted(emitted.items()):
        rel, line, kind = sites[0]
        kinds = {k for _, _, k in sites}
        if len(kinds) > 1:
            out.append(Finding(
                "RT006", rel, line,
                f"metric {name!r} emitted as {sorted(kinds)} — one name "
                "must stick to one kind (Prometheus rejects duplicates)",
            ))
        if name not in catalog:
            out.append(Finding(
                "RT006", rel, line,
                f"metric {name!r} is not in util/metrics.py "
                "BUILTIN_METRICS — register it (name + kind) so "
                "dashboards/alerts can rely on the catalog",
            ))
        elif catalog[name] not in kinds:
            out.append(Finding(
                "RT006", rel, line,
                f"metric {name!r} emitted as {sorted(kinds)[0]} but "
                f"cataloged as {catalog[name]} in BUILTIN_METRICS",
            ))
    for name in sorted(set(catalog) - set(emitted)):
        out.append(Finding(
            "RT006", metrics_mod.rel, 1,
            f"BUILTIN_METRICS row {name!r} is emitted nowhere — stale "
            "catalog entry (remove it, or restore the emitter)",
        ))
    return out
