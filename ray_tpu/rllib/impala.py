"""IMPALA: asynchronous env runners + V-trace off-policy learner.

Role-equivalent to the reference's IMPALA stack (reference:
rllib/algorithms/impala/impala.py:81-349 — async EnvRunner sampling into
bounded queues, a learner consuming off-policy batches, weight broadcast on
a cadence; rllib/execution/learner_thread.py). V-trace corrections follow
Espeholt et al. 2018 ("IMPALA: Scalable Distributed Deep-RL").

TPU-first divergences from the reference:
- The learner is ONE jitted function (loss + V-trace scan + optimizer) —
  no learner thread pool; under a Mesh the batch shards over dp/fsdp and
  XLA inserts the gradient psum (the multi-GPU learner-group analog).
- Asynchrony is pull-based: each runner keeps ``num_inflight`` sample calls
  in flight (per-actor FIFO pipelining), the driver consumes whichever
  fragment lands first and immediately resubmits — a bounded queue of
  ``num_runners * num_inflight`` fragments by construction, with sampling
  overlapping the learner update instead of aggregator actors + queues.
- Off-policyness is explicit: fragments carry the behavior policy's logp
  and a weights version; staleness is reported and corrected by V-trace.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from .env import VectorEnv


@ray_tpu.remote
class ImpalaEnvRunner:
    """Actor-side sampler: vectorized envs + a CPU copy of the policy.

    Unlike the PPO EnvRunner it returns the TRUE successor state per step
    (pre-reset where an episode ended) so the learner can evaluate V(x_{t+1})
    under the CURRENT parameters — V-trace needs learner-side values, not the
    behavior policy's (reference: vtrace uses values recomputed by the
    learner, impala_learner.py)."""

    def __init__(self, env_spec, num_envs: int, seed: int = 0):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.vec = VectorEnv(env_spec, num_envs, seed=seed)
        self.obs = self.vec.reset()
        self._forward = None
        self._params = None
        self._weights_version = -1
        self._rng = np.random.default_rng(seed + 1)

    def env_info(self) -> Dict[str, int]:
        return {
            "observation_size": self.vec.observation_size,
            "num_actions": self.vec.num_actions,
        }

    def set_weights(self, weights, version: int) -> bool:
        import jax.numpy as jnp

        from .learner import PolicyParams

        self._params = PolicyParams(*[jnp.asarray(w) for w in weights])
        self._weights_version = version
        return True

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """One [T, N] fragment under the runner's current (possibly stale)
        weights.  ``terminated`` masks bootstrap values; ``done``
        (terminated|truncated) cuts the V-trace recursion."""
        assert self._params is not None, "set_weights before sample"
        if self._forward is None:
            import jax

            from .learner import policy_forward

            self._forward = jax.jit(policy_forward)
        fwd = self._forward
        from .learner import sample_categorical
        N = self.vec.num_envs
        D = self.vec.observation_size
        obs_buf = np.empty((num_steps, N, D), np.float32)
        next_buf = np.empty((num_steps, N, D), np.float32)
        act_buf = np.empty((num_steps, N), np.int32)
        logp_buf = np.empty((num_steps, N), np.float32)
        rew_buf = np.empty((num_steps, N), np.float32)
        term_buf = np.empty((num_steps, N), np.bool_)
        done_buf = np.empty((num_steps, N), np.bool_)
        obs = self.obs
        for t in range(num_steps):
            logits, _ = fwd(self._params, obs)
            actions, logp = sample_categorical(logits, self._rng)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            obs, rewards, terms, truncs, final_obs = self.vec.step(actions)
            rew_buf[t] = rewards
            term_buf[t] = terms
            done_buf[t] = terms | truncs
            next_buf[t] = obs
            for i, o in final_obs.items():
                next_buf[t, i] = o  # true pre-reset successor
        self.obs = obs
        return {
            "obs": obs_buf,
            "next_obs": next_buf,
            "actions": act_buf,
            "logp_behavior": logp_buf,
            "rewards": rew_buf,
            "terminated": term_buf,
            "done": done_buf,
            "episode_returns": np.array(self.vec.drain_completed(),
                                        np.float64),
            "weights_version": self._weights_version,
        }


class ImpalaLearner:
    """V-trace actor-critic update as one jitted function (reference:
    impala_torch_learner.py compute_loss_for_module + vtrace_torch.py)."""

    def __init__(
        self,
        obs_size: int,
        num_actions: int,
        *,
        lr: float = 7e-4,
        gamma: float = 0.99,
        rho_bar: float = 1.0,
        c_bar: float = 1.0,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        grad_clip: float = 40.0,
        hidden: int = 64,
        seed: int = 0,
        mesh=None,
    ):
        import optax

        from .learner import init_policy

        self.params = init_policy(obs_size, num_actions, hidden, seed)
        self.tx = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adam(lr, eps=1e-5),
        )
        self.opt_state = self.tx.init(self.params)
        self.gamma = gamma
        self.rho_bar = rho_bar
        self.c_bar = c_bar
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.mesh = mesh
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        from .learner import policy_forward

        gamma, rho_bar, c_bar = self.gamma, self.rho_bar, self.c_bar
        vf_c, ent_c = self.vf_coeff, self.entropy_coeff
        tx = self.tx

        def loss_fn(params, batch):
            T, N = batch["rewards"].shape
            logits, values = policy_forward(params, batch["obs"])
            next_values = policy_forward(params, batch["next_obs"])[1]
            # Terminated: no bootstrap.  Truncated: V(true next state).
            next_values = next_values * (1.0 - batch["terminated"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1
            )[..., 0]
            # Importance ratios pi/mu on the chosen actions.
            ratio = jnp.exp(logp - batch["logp_behavior"])
            rho = jnp.minimum(jax.lax.stop_gradient(ratio), rho_bar)
            c = jnp.minimum(jax.lax.stop_gradient(ratio), c_bar)
            cont = 1.0 - batch["done"]  # episode boundary cuts the recursion
            v = jax.lax.stop_gradient(values)
            nv = jax.lax.stop_gradient(next_values)
            deltas = rho * (batch["rewards"] + gamma * nv - v)
            # vs_t - V_t = delta_t + gamma*cont_t*c_t*(vs_{t+1} - V_{t+1}),
            # reverse scan over time (Espeholt et al. eq. 1).
            def step(carry, x):
                delta, disc = x
                carry = delta + disc * carry
                return carry, carry

            _, vs_minus_v = jax.lax.scan(
                step, jnp.zeros((N,), values.dtype),
                (deltas, gamma * cont * c), reverse=True,
            )
            vs = v + vs_minus_v
            # Policy-gradient advantage: q_t = r_t + gamma*(V(x_{t+1}) +
            # cont*(vs_{t+1} - V_{t+1})); adv = rho*(q_t - V_t).
            vs_next_minus = jnp.concatenate(
                [vs_minus_v[1:], jnp.zeros((1, N), values.dtype)], axis=0
            )
            q = batch["rewards"] + gamma * (nv + cont * vs_next_minus)
            adv = rho * (q - v)
            pi_loss = -jnp.mean(logp * adv)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jax.nn.softmax(logits) * logp_all, axis=-1)
            )
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, {
                "policy_loss": pi_loss, "vf_loss": vf_loss,
                "entropy": entropy,
                "mean_rho": jnp.mean(jnp.minimum(ratio, rho_bar)),
            }

        from ..devtools import jitguard

        jitguard.register_program("impala_update")

        def update(params, opt_state, batch):
            # Trace-time only: joins the recompile sentinel (RT_DEBUG_JIT).
            jitguard.bump("impala_update", jitguard.signature_of(batch))
            (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        if self.mesh is not None:
            # Batch columns (env slots) shard over dp+fsdp; params stay
            # replicated; XLA inserts the gradient psum — the compiled
            # analog of the reference's multi-GPU learner DDP allreduce.
            from jax.sharding import NamedSharding, PartitionSpec as P

            col = NamedSharding(self.mesh, P(None, ("dp", "fsdp")))
            repl = NamedSharding(self.mesh, P())
            shardings = {
                "obs": col, "next_obs": col, "actions": col,
                "logp_behavior": col, "rewards": col,
                "terminated": col, "done": col,
            }
            return jax.jit(update, in_shardings=(repl, repl, shardings),
                           out_shardings=(repl, repl, None))
        return jax.jit(update)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def update_from_fragments(self, fragments: List[Dict[str, Any]]
                              ) -> Dict[str, float]:
        """One V-trace SGD step on fragments stacked along the env axis
        (single pass — IMPALA consumes each batch once, unlike PPO's
        epoch loop)."""
        import jax.numpy as jnp

        batch = {
            k: jnp.asarray(np.concatenate([f[k] for f in fragments], axis=1))
            for k in ("obs", "next_obs", "actions", "logp_behavior",
                      "rewards")
        }
        batch["terminated"] = jnp.asarray(np.concatenate(
            [f["terminated"] for f in fragments], axis=1).astype(np.float32))
        batch["done"] = jnp.asarray(np.concatenate(
            [f["done"] for f in fragments], axis=1).astype(np.float32))
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch
        )
        return {k: float(v) for k, v in aux.items()}


class ImpalaConfig:
    """Fluent config (reference: impala.py IMPALAConfig)."""

    def __init__(self):
        self.env_spec: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 64
        self.num_inflight_per_runner = 2
        self.fragments_per_update = 2
        self.updates_per_iteration = 8
        self.broadcast_interval = 1
        self.lr = 7e-4
        self.gamma = 0.99
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.hidden = 64
        self.seed = 0
        self.mesh = None

    def environment(self, env: Any) -> "ImpalaConfig":
        self.env_spec = env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 64,
                    num_inflight_per_runner: int = 2) -> "ImpalaConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        self.num_inflight_per_runner = num_inflight_per_runner
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 vf_coeff: Optional[float] = None,
                 rho_bar: Optional[float] = None,
                 c_bar: Optional[float] = None,
                 fragments_per_update: Optional[int] = None,
                 updates_per_iteration: Optional[int] = None,
                 broadcast_interval: Optional[int] = None,
                 mesh=None) -> "ImpalaConfig":
        for name, val in (("lr", lr), ("gamma", gamma),
                          ("entropy_coeff", entropy_coeff),
                          ("vf_coeff", vf_coeff), ("rho_bar", rho_bar),
                          ("c_bar", c_bar),
                          ("fragments_per_update", fragments_per_update),
                          ("updates_per_iteration", updates_per_iteration),
                          ("broadcast_interval", broadcast_interval),
                          ("mesh", mesh)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "Impala":
        return Impala(self)


class Impala:
    """The Algorithm: async sample -> V-trace update -> cadenced broadcast
    (reference: impala.py:81 training_step — sampling never blocks on the
    learner; the learner never waits for a full on-policy batch)."""

    def __init__(self, config: ImpalaConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        self.runners = [
            ImpalaEnvRunner.remote(
                config.env_spec, config.num_envs_per_runner,
                seed=config.seed + i,
            )
            for i in range(config.num_env_runners)
        ]
        info = ray_tpu.get(self.runners[0].env_info.remote())
        self.learner = ImpalaLearner(
            info["observation_size"], info["num_actions"],
            lr=config.lr, gamma=config.gamma, rho_bar=config.rho_bar,
            c_bar=config.c_bar, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff, grad_clip=config.grad_clip,
            hidden=config.hidden, seed=config.seed, mesh=config.mesh,
        )
        self.weights_version = 0
        self._broadcast(block=True)
        # Prime the pipeline: each runner keeps num_inflight sample calls
        # queued (per-actor FIFO), so sampling overlaps learner updates —
        # the bounded queue (reference: learner_thread inqueue).
        self._inflight: Dict[Any, int] = {}
        for i, r in enumerate(self.runners):
            for _ in range(config.num_inflight_per_runner):
                self._inflight[r.sample.remote(
                    config.rollout_fragment_length)] = i
        self.iteration = 0
        self.total_env_steps = 0
        self.total_updates = 0
        self._recent_returns: List[float] = []

    def _broadcast(self, block: bool = False):
        """Ship current learner weights to every runner (one object-store
        copy, reference: env_runner_group.sync_weights on a cadence)."""
        ref = ray_tpu.put(list(self.learner.get_weights()))
        calls = [r.set_weights.remote(ref, self.weights_version)
                 for r in self.runners]
        if block:
            ray_tpu.get(calls)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        staleness: List[int] = []
        learn_time = 0.0
        n_steps = 0
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            fragments = []
            while len(fragments) < cfg.fragments_per_update:
                done_refs, _ = ray_tpu.wait(
                    list(self._inflight), num_returns=1
                )
                ref = done_refs[0]
                idx = self._inflight.pop(ref)
                frag = ray_tpu.get(ref)
                # Immediately resubmit: the runner never idles.
                self._inflight[self.runners[idx].sample.remote(
                    cfg.rollout_fragment_length)] = idx
                fragments.append(frag)
                self._recent_returns.extend(
                    frag["episode_returns"].tolist())
                staleness.append(
                    self.weights_version - frag["weights_version"])
                n_steps += frag["rewards"].size
            t1 = time.perf_counter()
            metrics = self.learner.update_from_fragments(fragments)
            learn_time += time.perf_counter() - t1
            self.total_updates += 1
            self.weights_version += 1
            if self.total_updates % cfg.broadcast_interval == 0:
                self._broadcast(block=False)
        self._recent_returns = self._recent_returns[-100:]
        self.total_env_steps += n_steps
        self.iteration += 1
        wall = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": n_steps,
            "num_env_steps_sampled_lifetime": self.total_env_steps,
            "num_learner_updates_lifetime": self.total_updates,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")
            ),
            "env_steps_per_sec": n_steps / max(wall, 1e-9),
            "learner_updates_per_sec":
                cfg.updates_per_iteration / max(wall, 1e-9),
            "mean_weight_staleness":
                float(np.mean(staleness)) if staleness else 0.0,
            "time_learn_s": learn_time,
            **metrics,
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
