"""EnvRunner actor: vectorized rollout collection on CPU hosts.

Role-equivalent to the reference's SingleAgentEnvRunner
(reference: rllib/env/single_agent_env_runner.py:61 sample:131 — vectorized
envs, forward_exploration on the local policy copy, episode bookkeeping).
The runner holds a CPU copy of the policy; weights arrive via the object
store each iteration (reference: env_runner_group.sync_weights).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from .env import VectorEnv


@ray_tpu.remote
class EnvRunner:
    def __init__(self, env_spec, num_envs: int, seed: int = 0, model=None):
        import os

        # Runner policy inference is tiny; never let XLA grab host threads
        # aggressively or claim a TPU.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.vec = VectorEnv(env_spec, num_envs, seed=seed)
        self.obs = self.vec.reset()
        self.seed = seed
        # Optional models.* instance (cloudpickled in).  None = the legacy
        # MLP path where weights arrive as a PolicyParams field list.
        self.model = model
        self._forward = None
        self._params = None
        self._rng = np.random.default_rng(seed + 1)

    def _policy(self):
        if self._forward is None:
            import jax

            if self.model is not None:
                self._forward = jax.jit(self.model.apply)
            else:
                from .learner import policy_forward

                self._forward = jax.jit(policy_forward)
        return self._forward

    def set_weights(self, weights) -> bool:
        import jax

        import jax.numpy as jnp

        from .learner import PolicyParams

        if isinstance(weights, list):  # legacy flat field list
            weights = PolicyParams(*weights)
        self._params = jax.tree.map(jnp.asarray, weights)
        return True

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect [T, N] rollout fragments with logp/value for PPO
        (reference: sample:131 returns episode lists; here the batch format
        is the tensorized equivalent)."""
        assert self._params is not None, "set_weights before sample"
        from .learner import sample_categorical

        fwd = self._policy()
        N = self.vec.num_envs
        obs_buf = np.empty((num_steps, N, *self.vec.observation_shape),
                           np.float32)
        act_buf = np.empty((num_steps, N), np.int32)
        logp_buf = np.empty((num_steps, N), np.float32)
        val_buf = np.empty((num_steps, N), np.float32)
        rew_buf = np.empty((num_steps, N), np.float32)
        done_buf = np.empty((num_steps, N), np.bool_)
        # V(s_{t+1}) per row with episode semantics (see compute_gae):
        # default = next row's value (filled after the loop); terminal = 0;
        # truncated = V(true pre-reset state).
        boot_buf = np.zeros((num_steps, N), np.float32)
        boot_override: dict = {}
        obs = self.obs
        for t in range(num_steps):
            logits, value = fwd(self._params, obs)
            actions, logp = sample_categorical(logits, self._rng)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            val_buf[t] = np.asarray(value)
            obs, rewards, terms, truncs, final_obs = self.vec.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = terms | truncs
            for i, o in final_obs.items():
                # Terminated: bootstrap 0.  Truncated: V(true next state).
                boot_override[(t, i)] = None if terms[i] else o
        self.obs = obs
        _, last_value = fwd(self._params, obs)
        last_value = np.asarray(last_value)
        boot_buf[:-1] = val_buf[1:]
        boot_buf[-1] = last_value
        if boot_override:
            keys = [(t, i) for (t, i), o in boot_override.items()
                    if o is not None]
            if keys:
                finals = np.stack([boot_override[k] for k in keys])
                _, v_final = fwd(self._params, finals)
                v_final = np.asarray(v_final)
                for (t, i), v in zip(keys, v_final):
                    boot_buf[t, i] = v
            for (t, i), o in boot_override.items():
                if o is None:
                    boot_buf[t, i] = 0.0
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp_old": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "bootstrap_values": boot_buf,
            "episode_returns": np.array(self.vec.drain_completed(),
                                        np.float64),
        }

    def env_info(self) -> Dict[str, Any]:
        return {
            "observation_size": self.vec.observation_size,
            "observation_shape": self.vec.observation_shape,
            "num_actions": self.vec.num_actions,
        }

    # -- off-policy sampling (DQN-family) ------------------------------------

    def set_q_weights(self, weights) -> bool:
        """Install Q-network params (a QParams pytree from rllib.dqn)."""
        import jax.numpy as jnp

        from .dqn import QParams

        self._params = QParams(*[jnp.asarray(w) for w in weights])
        return True

    def sample_transitions(self, num_steps: int,
                           epsilon: float) -> Dict[str, np.ndarray]:
        """Collect flat (s, a, r, s', done) transitions with epsilon-greedy
        exploration for replay-buffer algorithms (reference:
        single_agent_env_runner.py:131 sample — episodes are post-processed
        into transition batches by the DQN pipeline; here the runner emits
        transitions directly).

        ``done`` marks *termination only*: a time-limit truncation still
        bootstraps from V/Q of the true next state (same semantics as the
        PPO path's bootstrap_values)."""
        assert self._params is not None, "set_q_weights before sample"
        if getattr(self, "_q_forward", None) is None:
            import jax

            from .dqn import q_forward

            self._q_forward = jax.jit(q_forward)
        fwd = self._q_forward
        N = self.vec.num_envs
        D = self.vec.observation_size
        obs_buf = np.empty((num_steps, N, D), np.float32)
        next_buf = np.empty((num_steps, N, D), np.float32)
        act_buf = np.empty((num_steps, N), np.int32)
        rew_buf = np.empty((num_steps, N), np.float32)
        done_buf = np.empty((num_steps, N), np.float32)
        obs = self.obs
        for t in range(num_steps):
            q = np.asarray(fwd(self._params, obs))
            actions = np.argmax(q, axis=-1).astype(np.int32)
            explore = self._rng.random(N) < epsilon
            actions = np.where(
                explore,
                self._rng.integers(0, self.vec.num_actions, N),
                actions,
            ).astype(np.int32)
            obs_buf[t] = obs
            act_buf[t] = actions
            obs, rewards, terms, truncs, final_obs = self.vec.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = terms.astype(np.float32)
            next_buf[t] = obs
            for i, o in final_obs.items():
                next_buf[t, i] = o  # true pre-reset successor state
        self.obs = obs
        return {
            "obs": obs_buf.reshape(num_steps * N, D),
            "next_obs": next_buf.reshape(num_steps * N, D),
            "actions": act_buf.reshape(-1),
            "rewards": rew_buf.reshape(-1),
            "dones": done_buf.reshape(-1),
            "episode_returns": np.array(self.vec.drain_completed(),
                                        np.float64),
        }
