"""PPO algorithm: sample -> update -> weight-sync over EnvRunner actors.

Role-equivalent to the reference's new-API-stack PPO
(reference: rllib/algorithms/ppo/ppo.py:444-520 training_step:
synchronous_parallel_sample over the EnvRunnerGroup ->
learner_group.update_from_episodes -> env_runner_group.sync_weights), with
the JAX learner on the driver (single host) or pjit-sharded over a Mesh.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from .env import make_env
from .env_runner import EnvRunner
from .learner import PPOLearner, compute_gae


class PPOConfig:
    """Fluent config (reference: algorithm_config.py AlgorithmConfig)."""

    def __init__(self):
        self.env_spec: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 0.5
        self.num_epochs = 10
        self.minibatch_size = 256
        self.hidden = 64
        self.seed = 0
        self.mesh = None
        # Optional models.* instance; None = pick by obs shape (MLP for 1D,
        # CNN for image observations — reference: catalog.py dispatch).
        self.model_spec = None

    def environment(self, env: Any) -> "PPOConfig":
        self.env_spec = env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 64) -> "PPOConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 lambda_: Optional[float] = None,
                 clip_param: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 num_epochs: Optional[int] = None,
                 minibatch_size: Optional[int] = None,
                 mesh=None, model=None) -> "PPOConfig":
        for name, val in (("lr", lr), ("gamma", gamma), ("lambda_", lambda_),
                          ("clip_param", clip_param),
                          ("entropy_coeff", entropy_coeff),
                          ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size),
                          ("mesh", mesh), ("model_spec", model)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """The Algorithm (reference: algorithms/algorithm.py:227 — a Trainable
    whose step() is one sample/update/sync round)."""

    def __init__(self, config: PPOConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        # Driver-side env probe: obs/action spaces come from a local env
        # instance, not a throwaway actor (reference: the algorithm reads
        # spaces from the env spec before building the EnvRunnerGroup).
        probe_env = make_env(config.env_spec, seed=config.seed)
        info = {
            "observation_size": probe_env.observation_size,
            "observation_shape": tuple(getattr(
                probe_env, "observation_shape",
                (probe_env.observation_size,))),
            "num_actions": probe_env.num_actions,
        }
        del probe_env
        model = config.model_spec
        if model is None:
            from .models import default_model

            model = default_model(info["observation_shape"],
                                  info["num_actions"], config.hidden)
        self.runners = [
            EnvRunner.remote(config.env_spec, config.num_envs_per_runner,
                             seed=config.seed + i, model=model)
            for i in range(config.num_env_runners)
        ]
        self.learner = PPOLearner(
            info["observation_size"], info["num_actions"],
            lr=config.lr, clip_param=config.clip_param,
            vf_coeff=config.vf_coeff, entropy_coeff=config.entropy_coeff,
            grad_clip=config.grad_clip, hidden=config.hidden,
            seed=config.seed, mesh=config.mesh, model=model,
        )
        self._sync_weights()
        self.iteration = 0
        self.total_env_steps = 0
        self._recent_returns: List[float] = []

    def _sync_weights(self):
        """Broadcast learner weights once via the object store; every runner
        reads the same copy (reference: env_runner_group.sync_weights)."""
        ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners])

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: ppo.py:444 training_step)."""
        cfg = self.config
        t0 = time.perf_counter()
        samples = ray_tpu.get([
            r.sample.remote(cfg.rollout_fragment_length)
            for r in self.runners
        ])
        sample_time = time.perf_counter() - t0

        # Stitch runner fragments: GAE per runner (each has its own
        # last_values), then flatten [T, N] -> rows.
        flat: Dict[str, List[np.ndarray]] = {
            "obs": [], "actions": [], "logp_old": [],
            "advantages": [], "returns": [],
        }
        for s in samples:
            adv, ret = compute_gae(
                s["rewards"], s["values"], s["bootstrap_values"], s["dones"],
                cfg.gamma, cfg.lambda_,
            )
            T, N = s["rewards"].shape
            flat["obs"].append(s["obs"].reshape(T * N, *s["obs"].shape[2:]))
            flat["actions"].append(s["actions"].reshape(-1))
            flat["logp_old"].append(s["logp_old"].reshape(-1))
            flat["advantages"].append(adv.reshape(-1))
            flat["returns"].append(ret.reshape(-1))
            self._recent_returns.extend(s["episode_returns"].tolist())
        batch = {k: np.concatenate(v) for k, v in flat.items()}
        self._recent_returns = self._recent_returns[-100:]

        t1 = time.perf_counter()
        metrics = self.learner.update_from_batch(
            batch,
            num_epochs=cfg.num_epochs,
            minibatch_size=min(cfg.minibatch_size, len(batch["obs"])),
            seed=cfg.seed + self.iteration,
        )
        learn_time = time.perf_counter() - t1
        self._sync_weights()

        n_steps = len(batch["obs"])
        self.total_env_steps += n_steps
        self.iteration += 1
        wall = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": n_steps,
            "num_env_steps_sampled_lifetime": self.total_env_steps,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")
            ),
            "env_steps_per_sec": n_steps / max(wall, 1e-9),
            "time_sample_s": sample_time,
            "time_learn_s": learn_time,
            **metrics,
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # -- Tune integration (Algorithm is a trainable) ------------------------

    @classmethod
    def as_trainable(cls, config: PPOConfig, stop_iters: int = 50,
                     stop_reward: Optional[float] = None):
        """A function trainable for ray_tpu.tune (reference: Algorithm is a
        Trainable; tune runs algo.train() in a loop)."""

        def trainable(tune_config):
            from ray_tpu import tune as rt_tune

            algo = cls(config)
            try:
                result: Dict[str, Any] = {}
                for _ in range(stop_iters):
                    result = algo.train()
                    rt_tune.report(result)
                    if (stop_reward is not None
                            and result["episode_return_mean"] >= stop_reward):
                        break
                return result
            finally:
                algo.stop()

        return trainable
