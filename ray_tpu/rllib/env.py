"""Built-in environments + vectorization.

Role-equivalent to the reference's env layer (reference:
rllib/env/single_agent_env_runner.py:756-806 wraps gym.vector envs).  The
image has no gymnasium, so the classic CartPole dynamics (public textbook
equations, same constants as gym's cartpole.py) are implemented here; any
object with reset(seed)/step(action) and observation_size/num_actions works
as an env.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np


class CartPoleEnv:
    """CartPole-v1 semantics: episode ends past +/-2.4m or +/-12deg or 500
    steps; reward 1 per step (solved ~= 475+)."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    MASS_CART = 1.0
    MASS_POLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * math.pi / 180

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros(4, np.float32)
        self.steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self.steps = 0
        return self.state.copy()

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, bool]:
        x, x_dot, theta, theta_dot = self.state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.MASS_CART + self.MASS_POLE
        pole_mass_length = self.MASS_POLE * self.LENGTH
        cos_t, sin_t = math.cos(theta), math.sin(theta)
        temp = (force + pole_mass_length * theta_dot**2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASS_POLE * cos_t**2 / total_mass)
        )
        x_acc = temp - pole_mass_length * theta_acc * cos_t / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self.state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self.steps += 1
        terminated = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
        )
        truncated = self.steps >= self.max_episode_steps
        return self.state.copy(), 1.0, terminated, truncated


class CatchEnv:
    """Pixel-observation Catch (the bsuite/DeepMind-classic test problem):
    a ball falls from a random top column; the paddle on the bottom row
    moves left/stay/right; terminal reward +1 on catch, -1 on miss.
    Observations are a (rows, cols, 1) float image — exercises the conv
    policy path (reference: image envs routed to conv nets via
    models/utils.py get_filter_config; benchmark_atari_ppo.py is the
    conv-scale benchmark)."""

    ROWS = 10
    COLS = 5
    observation_shape = (ROWS, COLS, 1)
    observation_size = ROWS * COLS
    num_actions = 3
    max_episode_steps = ROWS  # ball reaches the bottom in ROWS-1 steps

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.ball_row = 0
        self.ball_col = 0
        self.paddle = 0
        self.steps = 0

    def _obs(self) -> np.ndarray:
        img = np.zeros(self.observation_shape, np.float32)
        img[self.ball_row, self.ball_col, 0] = 1.0
        img[self.ROWS - 1, self.paddle, 0] = 1.0
        return img

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.ball_row = 0
        self.ball_col = int(self.rng.integers(0, self.COLS))
        self.paddle = self.COLS // 2
        self.steps = 0
        return self._obs()

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, bool]:
        self.paddle = int(np.clip(self.paddle + (action - 1), 0,
                                  self.COLS - 1))
        self.ball_row += 1
        self.steps += 1
        if self.ball_row == self.ROWS - 1:
            reward = 1.0 if self.paddle == self.ball_col else -1.0
            return self._obs(), reward, True, False
        return self._obs(), 0.0, False, False


ENV_REGISTRY = {"CartPole-v1": CartPoleEnv, "Catch-v0": CatchEnv}


def register_env(name: str, cls) -> None:
    ENV_REGISTRY[name] = cls


def make_env(spec, seed: Optional[int] = None):
    if isinstance(spec, str):
        return ENV_REGISTRY[spec](seed=seed)
    return spec(seed=seed)


class VectorEnv:
    """N independent env copies stepped together with auto-reset (the
    reference's gym.vector.SyncVectorEnv role)."""

    def __init__(self, spec, num_envs: int, seed: int = 0):
        self.envs: List = [
            make_env(spec, seed=seed * 10_000 + i) for i in range(num_envs)
        ]
        self.num_envs = num_envs
        self.observation_size = self.envs[0].observation_size
        # Image envs expose observation_shape (H, W, C); 1D envs fall back
        # to (observation_size,).  Everything downstream keys off the shape.
        self.observation_shape = tuple(getattr(
            self.envs[0], "observation_shape", (self.observation_size,)))
        self.num_actions = self.envs[0].num_actions
        self.episode_returns = np.zeros(num_envs, np.float64)
        self.completed_returns: List[float] = []

    def reset(self) -> np.ndarray:
        self.episode_returns[:] = 0.0
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions: np.ndarray):
        """Returns (obs, rewards, terminateds, truncateds, final_obs):
        terminated and truncated are separate (truncated episodes must
        bootstrap from the true next state, not be treated as terminal —
        the gymnasium v26 semantics); final_obs holds the pre-reset next
        observation for done envs."""
        obs, rewards, terms, truncs = [], [], [], []
        final_obs: dict = {}
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            o, r, term, trunc = env.step(int(a))
            self.episode_returns[i] += r
            if term or trunc:
                self.completed_returns.append(self.episode_returns[i])
                self.episode_returns[i] = 0.0
                final_obs[i] = o
                o = env.reset()
            obs.append(o)
            rewards.append(r)
            terms.append(term)
            truncs.append(trunc)
        return (
            np.stack(obs),
            np.array(rewards, np.float32),
            np.array(terms, np.bool_),
            np.array(truncs, np.bool_),
            final_obs,
        )

    def drain_completed(self) -> List[float]:
        out, self.completed_returns = self.completed_returns, []
        return out
