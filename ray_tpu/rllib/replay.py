"""Replay buffer for off-policy algorithms.

Role-equivalent to the reference's replay buffers
(reference: rllib/utils/replay_buffers/replay_buffer.py ReplayBuffer with
uniform sampling; episode/prioritized variants build on it) — re-designed as
flat preallocated numpy rings: transitions arrive as whole [B] batches from
vectorized EnvRunners, so insertion is a slice copy, and sampled minibatches
go straight to `jnp.asarray` with static shapes for the jitted update.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int, obs_size: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.next_obs = np.zeros((capacity, obs_size), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Insert [B] transitions, wrapping the ring as needed."""
        n = len(batch["actions"])
        start = 0
        while start < n:
            room = min(n - start, self.capacity - self._idx)
            sl = slice(self._idx, self._idx + room)
            bl = slice(start, start + room)
            self.obs[sl] = batch["obs"][bl]
            self.next_obs[sl] = batch["next_obs"][bl]
            self.actions[sl] = batch["actions"][bl]
            self.rewards[sl] = batch["rewards"][bl]
            self.dones[sl] = batch["dones"][bl]
            self._idx = (self._idx + room) % self.capacity
            self._size = min(self._size + room, self.capacity)
            start += room

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }
