"""Multi-agent environments + runner + PPO.

Role-equivalent to the reference's multi-agent stack (reference:
rllib/env/multi_agent_env.py:31 MultiAgentEnv — dict obs/action/reward
keyed by agent id, per-agent termination plus the "__all__" flag;
rllib/env/multi_agent_env_runner.py — one env per runner, episodes routed
to policies via policy_mapping_fn; multi-agent PPO trains one learner per
policy from its agents' experience).

Design differences from the reference: trajectories are tensorized per
policy inside the runner (GAE computed runner-side at fragment boundaries,
so ragged per-agent episodes never ship), and each policy's learner is the
same jitted PPOLearner used single-agent — a policy is a (model, params)
pair, so heterogeneous architectures per policy work out of the box.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from .env import CartPoleEnv, make_env


class MultiAgentEnv:
    """Protocol: subclasses define possible_agents and per-agent spaces.

    reset(seed) -> {agent_id: obs}
    step({agent_id: action}) -> (obs_d, reward_d, terminated_d, truncated_d)
      where terminated_d/truncated_d carry per-agent flags plus "__all__".
    Only agents present in the returned obs dict act next step; an agent
    absent from obs but present in reward_d receives its final reward
    (reference: multi_agent_env.py:96 step docs).
    """

    possible_agents: List[str] = []

    def observation_shape(self, agent_id: str) -> Tuple[int, ...]:
        raise NotImplementedError

    def num_actions(self, agent_id: str) -> int:
        raise NotImplementedError

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles, one per agent; agents terminate individually
    and the episode ends when all have (reference:
    rllib/examples/envs/classes/multi_agent.py MultiAgentCartPole)."""

    def __init__(self, num_agents: int = 2, seed: Optional[int] = None):
        self.possible_agents = [f"agent_{i}" for i in range(num_agents)]
        base = 0 if seed is None else seed
        self.envs = {
            a: CartPoleEnv(seed=base * 1000 + i)
            for i, a in enumerate(self.possible_agents)
        }
        self.done: Dict[str, bool] = {}

    def observation_shape(self, agent_id: str) -> Tuple[int, ...]:
        return (4,)

    def num_actions(self, agent_id: str) -> int:
        return 2

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        self.done = {a: False for a in self.possible_agents}
        return {
            a: env.reset(None if seed is None else seed + i)
            for i, (a, env) in enumerate(self.envs.items())
        }

    def step(self, actions: Dict[str, int]):
        obs, rew, term, trunc = {}, {}, {}, {}
        for a, act in actions.items():
            if self.done[a]:
                continue
            o, r, te, tr = self.envs[a].step(act)
            rew[a] = r
            term[a] = te
            trunc[a] = tr
            if te or tr:
                self.done[a] = True
            else:
                obs[a] = o
        term["__all__"] = all(self.done.values())
        trunc["__all__"] = False
        return obs, rew, term, trunc


MULTI_ENV_REGISTRY: Dict[str, Any] = {
    "MultiAgentCartPole": MultiAgentCartPole,
}


def make_multi_env(spec, **kwargs):
    if isinstance(spec, str):
        return MULTI_ENV_REGISTRY[spec](**kwargs)
    return spec(**kwargs)


class _AgentFragment:
    """Per-agent trajectory accumulator inside one runner fragment."""

    __slots__ = ("obs", "actions", "logp", "values", "rewards")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logp: List[float] = []
        self.values: List[float] = []
        self.rewards: List[float] = []


@ray_tpu.remote
class MultiAgentEnvRunner:
    """One multi-agent env per runner (reference:
    multi_agent_env_runner.py — multi-agent envs aren't vectorized; scale
    comes from more runner actors).  Emits per-POLICY training rows with
    GAE already applied, so ragged per-agent episodes never cross the wire.
    """

    def __init__(self, env_spec, policy_mapping: Dict[str, str],
                 models: Dict[str, Any], *, gamma: float = 0.99,
                 lambda_: float = 0.95, seed: int = 0, env_kwargs=None):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.env = make_multi_env(env_spec, **(env_kwargs or {}))
        self.policy_mapping = dict(policy_mapping)
        self.models = models
        self.gamma = gamma
        self.lambda_ = lambda_
        self._rng = np.random.default_rng(seed + 1)
        self._seed = seed
        self._params: Dict[str, Any] = {}
        self._fwd: Dict[str, Any] = {}
        self.obs = self.env.reset(seed=seed)
        self._episode_return = {a: 0.0 for a in self.env.possible_agents}
        self.completed_returns: List[float] = []

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        import jax
        import jax.numpy as jnp

        for pid, w in weights.items():
            self._params[pid] = jax.tree.map(jnp.asarray, w)
        return True

    def _forward(self, pid: str):
        if pid not in self._fwd:
            import jax

            self._fwd[pid] = jax.jit(self._models_apply(pid))
        return self._fwd[pid]

    def _models_apply(self, pid: str):
        return self.models[pid].apply

    def env_info(self) -> Dict[str, Any]:
        env = self.env
        return {
            "agents": list(env.possible_agents),
            "observation_shapes": {
                a: tuple(env.observation_shape(a))
                for a in env.possible_agents
            },
            "num_actions": {
                a: env.num_actions(a) for a in env.possible_agents
            },
        }

    def sample(self, num_steps: int) -> Dict[str, Dict[str, np.ndarray]]:
        """Run num_steps env steps; return {policy_id: rows} where rows are
        flat {obs, actions, logp_old, advantages, returns} plus metrics."""
        from .learner import compute_gae, sample_categorical

        frags: Dict[str, _AgentFragment] = {}
        out: Dict[str, Dict[str, List]] = {
            pid: {"obs": [], "actions": [], "logp_old": [],
                  "advantages": [], "returns": []}
            for pid in self.models
        }

        def finish(agent: str, bootstrap: float):
            """Close an agent trajectory: GAE with the given bootstrap for
            the final step, then append rows to its policy's buffers."""
            fr = frags.pop(agent, None)
            if fr is None or not fr.actions:
                return
            T = len(fr.actions)
            rewards = np.asarray(fr.rewards, np.float32)[:, None]
            values = np.asarray(fr.values, np.float32)[:, None]
            # bootstrap_values[t] = V(s_{t+1}): next row's value inside the
            # fragment, the provided bootstrap for the last row.
            boot = np.empty((T, 1), np.float32)
            boot[:-1, 0] = values[1:, 0]
            boot[-1, 0] = bootstrap
            dones = np.zeros((T, 1), np.bool_)
            dones[-1, 0] = True  # cut the recursion at the fragment edge
            adv, ret = compute_gae(rewards, values, boot, dones,
                                   self.gamma, self.lambda_)
            pid = self.policy_mapping[agent]
            out[pid]["obs"].extend(fr.obs)
            out[pid]["actions"].extend(fr.actions)
            out[pid]["logp_old"].extend(fr.logp)
            out[pid]["advantages"].extend(adv[:, 0].tolist())
            out[pid]["returns"].extend(ret[:, 0].tolist())

        for _ in range(num_steps):
            if not self.obs:  # every agent done: episode rolls over
                self.obs = self.env.reset()
                for a in self._episode_return:
                    self._episode_return[a] = 0.0
            # Group live agents by policy for batched forward passes.
            by_policy: Dict[str, List[str]] = {}
            for a in self.obs:
                by_policy.setdefault(self.policy_mapping[a], []).append(a)
            actions: Dict[str, int] = {}
            step_info: Dict[str, Tuple[int, float, float]] = {}
            for pid, agents in by_policy.items():
                stack = np.stack([self.obs[a] for a in agents])
                logits, value = self._forward(pid)(self._params[pid], stack)
                acts, logps = sample_categorical(logits, self._rng)
                value = np.asarray(value)
                for i, a in enumerate(agents):
                    actions[a] = int(acts[i])
                    step_info[a] = (int(acts[i]), float(logps[i]),
                                    float(value[i]))
            prev_obs = self.obs
            next_obs, rewards, terms, truncs = self.env.step(actions)
            for a, (act, logp, val) in step_info.items():
                fr = frags.setdefault(a, _AgentFragment())
                fr.obs.append(prev_obs[a])
                fr.actions.append(act)
                fr.logp.append(logp)
                fr.values.append(val)
                fr.rewards.append(rewards.get(a, 0.0))
                self._episode_return[a] += rewards.get(a, 0.0)
                if terms.get(a):
                    self.completed_returns.append(self._episode_return[a])
                    finish(a, 0.0)
                elif truncs.get(a):
                    # Truncated without a successor obs in this protocol:
                    # bootstrap from the last value estimate.
                    self.completed_returns.append(self._episode_return[a])
                    finish(a, val)
            # Protocol: an agent absent from obs (it didn't act this step)
            # may still receive a (final) reward — e.g. turn-based envs
            # deliver it one step late.  Credit it to the agent's LAST
            # acted step (multi_agent_env.py:96 step docs).
            for a, r in rewards.items():
                if a in step_info:
                    continue
                fr = frags.get(a)
                if fr is not None and fr.rewards:
                    fr.rewards[-1] += r
                self._episode_return[a] = (
                    self._episode_return.get(a, 0.0) + r)
                if terms.get(a) or truncs.get(a):
                    self.completed_returns.append(self._episode_return[a])
                    finish(a, 0.0 if terms.get(a)
                           else (fr.values[-1] if fr and fr.values else 0.0))
            self.obs = next_obs

        # Fragment boundary: bootstrap live agents from V(current obs).
        for a in list(frags):
            pid = self.policy_mapping[a]
            if a in self.obs:
                _, v = self._forward(pid)(
                    self._params[pid], self.obs[a][None])
                finish(a, float(np.asarray(v)[0]))
            else:
                finish(a, 0.0)

        result: Dict[str, Any] = {}
        for pid, rows in out.items():
            if rows["actions"]:
                result[pid] = {
                    "obs": np.asarray(rows["obs"], np.float32),
                    "actions": np.asarray(rows["actions"], np.int32),
                    "logp_old": np.asarray(rows["logp_old"], np.float32),
                    "advantages": np.asarray(rows["advantages"], np.float32),
                    "returns": np.asarray(rows["returns"], np.float32),
                }
        drained, self.completed_returns = self.completed_returns, []
        result["__metrics__"] = {
            "episode_returns": np.asarray(drained, np.float64),
        }
        return result


class MultiAgentPPOConfig:
    """Fluent config for multi-agent PPO (reference: AlgorithmConfig
    .multi_agent(policies=..., policy_mapping_fn=...))."""

    def __init__(self):
        self.env_spec: Any = "MultiAgentCartPole"
        self.env_kwargs: Dict[str, Any] = {}
        self.num_env_runners = 2
        self.rollout_fragment_length = 256
        self.policies: List[str] = []
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.num_epochs = 10
        self.minibatch_size = 128
        self.hidden = 64
        self.seed = 0
        self.models: Dict[str, Any] = {}

    def environment(self, env: Any, **env_kwargs) -> "MultiAgentPPOConfig":
        self.env_spec = env
        self.env_kwargs = env_kwargs
        return self

    def multi_agent(self, *, policies: List[str],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, num_env_runners: int = 2,
                    rollout_fragment_length: int = 256
                    ) -> "MultiAgentPPOConfig":
        self.num_env_runners = num_env_runners
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "MultiAgentPPOConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One PPOLearner per policy; runners route experience by
    policy_mapping_fn (reference: ppo.py training_step over a
    MultiAgentEpisode buffer + one Learner per module in the LearnerGroup).
    """

    def __init__(self, config: MultiAgentPPOConfig):
        from .learner import PPOLearner
        from .models import default_model

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        probe_env = make_multi_env(config.env_spec, **config.env_kwargs)
        agents = list(probe_env.possible_agents)
        if not config.policies:
            config.policies = ["shared"]
        mapping_fn = config.policy_mapping_fn or (lambda a: config.policies[0])
        self.policy_mapping = {a: mapping_fn(a) for a in agents}
        unknown = set(self.policy_mapping.values()) - set(config.policies)
        assert not unknown, f"mapping produced unknown policies: {unknown}"

        # Per-policy spaces must agree across that policy's agents.
        self.models: Dict[str, Any] = {}
        self.learners: Dict[str, PPOLearner] = {}
        for pid in config.policies:
            pid_agents = [a for a, p in self.policy_mapping.items()
                          if p == pid]
            if not pid_agents:
                continue
            shapes = {tuple(probe_env.observation_shape(a))
                      for a in pid_agents}
            acts = {probe_env.num_actions(a) for a in pid_agents}
            assert len(shapes) == 1 and len(acts) == 1, (
                f"policy {pid!r} maps agents with mismatched spaces: "
                f"{shapes} / {acts}")
            obs_shape, n_actions = shapes.pop(), acts.pop()
            model = config.models.get(pid) or default_model(
                obs_shape, n_actions, config.hidden)
            self.models[pid] = model
            self.learners[pid] = PPOLearner(
                int(np.prod(obs_shape)), n_actions, lr=config.lr,
                clip_param=config.clip_param,
                entropy_coeff=config.entropy_coeff, hidden=config.hidden,
                # Stable per-policy seed: list position, not hash() (which
                # is salted per process and would break reproducibility).
                seed=config.seed + config.policies.index(pid), model=model,
            )

        self.runners = [
            MultiAgentEnvRunner.remote(
                config.env_spec, self.policy_mapping, self.models,
                gamma=config.gamma, lambda_=config.lambda_,
                seed=config.seed + i, env_kwargs=config.env_kwargs,
            )
            for i in range(config.num_env_runners)
        ]
        self._sync_weights()
        self.iteration = 0
        self.total_env_steps = 0
        self._recent_returns: List[float] = []

    def _sync_weights(self):
        ref = ray_tpu.put({
            pid: ln.get_weights() for pid, ln in self.learners.items()
        })
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners])

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        samples = ray_tpu.get([
            r.sample.remote(cfg.rollout_fragment_length)
            for r in self.runners
        ])
        metrics: Dict[str, Any] = {}
        n_rows = 0
        for pid, learner in self.learners.items():
            parts = [s[pid] for s in samples if pid in s]
            if not parts:
                continue
            batch = {
                k: np.concatenate([p[k] for p in parts])
                for k in parts[0]
            }
            n_rows += len(batch["actions"])
            pm = learner.update_from_batch(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=min(cfg.minibatch_size,
                                   len(batch["actions"])),
                seed=cfg.seed + self.iteration,
            )
            metrics[pid] = pm
        for s in samples:
            self._recent_returns.extend(
                s["__metrics__"]["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-200:]
        self._sync_weights()
        self.iteration += 1
        self.total_env_steps += n_rows
        wall = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": n_rows,
            "num_env_steps_sampled_lifetime": self.total_env_steps,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")
            ),
            "env_steps_per_sec": n_rows / max(wall, 1e-9),
            "policies": metrics,
        }

    def get_policy_weights(self, pid: str):
        return self.learners[pid].get_weights()

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
