"""SAC: continuous-action soft actor-critic with a squashed-Gaussian policy.

Role-equivalent to the reference's SAC
(reference: rllib/algorithms/sac/sac.py:31 — off-policy, twin Q networks,
tanh-squashed Gaussian actor, automatic entropy-temperature tuning
sac.py:524 validates continuous action spaces; sac_torch_learner computes
the actor/critic/alpha losses).  TPU-first shape: the entire update (actor,
twin critics, alpha, polyak targets) is ONE jitted function; the replay
batch is the only host<->device traffic.

Includes PendulumEnv — the classic continuous-control benchmark (public
textbook dynamics, same constants as gym's pendulum.py) since the image
carries no gymnasium.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import ray_tpu


class PendulumEnv:
    """Pendulum-v1 semantics: swing up and hold; obs [cos th, sin th,
    thdot], action torque in [-2, 2], reward -(th^2 + .1 thdot^2 + .001
    a^2), 200-step episodes (truncation only)."""

    observation_size = 3
    observation_shape = (3,)
    action_size = 1
    action_low = -2.0
    action_high = 2.0
    max_episode_steps = 200

    MAX_SPEED = 8.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.th = 0.0
        self.thdot = 0.0
        self.steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([math.cos(self.th), math.sin(self.th), self.thdot],
                        np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.th = float(self.rng.uniform(-math.pi, math.pi))
        self.thdot = float(self.rng.uniform(-1.0, 1.0))
        self.steps = 0
        return self._obs()

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool]:
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        th_norm = ((self.th + math.pi) % (2 * math.pi)) - math.pi
        cost = th_norm**2 + 0.1 * self.thdot**2 + 0.001 * u**2
        acc = (3 * self.G / (2 * self.L) * math.sin(self.th)
               + 3.0 / (self.M * self.L**2) * u)
        self.thdot = float(np.clip(self.thdot + acc * self.DT,
                                   -self.MAX_SPEED, self.MAX_SPEED))
        self.th += self.thdot * self.DT
        self.steps += 1
        return self._obs(), -cost, False, self.steps >= self.max_episode_steps


class SACParams(NamedTuple):
    actor: Any
    q1: Any
    q2: Any
    q1_target: Any
    q2_target: Any
    log_alpha: Any


def _mlp_init(key, sizes):
    import jax

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    he = jax.nn.initializers.he_normal()
    for k, (m, n) in zip(keys, zip(sizes[:-1], sizes[1:])):
        import jax.numpy as jnp

        params.append({"w": he(k, (m, n), jnp.float32),
                       "b": jnp.zeros(n)})
    return params


def _mlp_apply(params, x, final_act=None):
    import jax

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
        elif final_act is not None:
            x = final_act(x)
    return x


class SACLearner:
    """Twin-Q + squashed-Gaussian actor + auto-alpha, one jitted update.

    reference: sac_torch_learner.py compute_loss_for_module — critic target
    uses min(Q1', Q2') - alpha * logp of a fresh next-action sample; actor
    maximizes min(Q) - alpha * logp; alpha tracks -|A| target entropy."""

    LOG_STD_MIN = -20.0
    LOG_STD_MAX = 2.0

    def __init__(self, obs_size: int, action_size: int, *,
                 action_low: float, action_high: float,
                 lr: float = 3e-4, gamma: float = 0.99, tau: float = 0.005,
                 hidden: int = 256, seed: int = 0,
                 target_entropy: Optional[float] = None):
        import jax
        import jax.numpy as jnp
        import optax

        self.gamma = gamma
        self.tau = tau
        self.action_size = action_size
        self.scale = (action_high - action_low) / 2.0
        self.bias = (action_high + action_low) / 2.0
        self.target_entropy = (-float(action_size)
                               if target_entropy is None else target_entropy)
        k = jax.random.split(jax.random.PRNGKey(seed), 3)
        actor = _mlp_init(k[0], [obs_size, hidden, hidden, 2 * action_size])
        q1 = _mlp_init(k[1], [obs_size + action_size, hidden, hidden, 1])
        q2 = _mlp_init(k[2], [obs_size + action_size, hidden, hidden, 1])
        self.params = SACParams(
            actor=actor, q1=q1, q2=q2,
            q1_target=jax.tree.map(lambda x: x, q1),
            q2_target=jax.tree.map(lambda x: x, q2),
            log_alpha=jnp.zeros(()),
        )
        self.tx = optax.adam(lr)
        self.opt_state = {
            "actor": self.tx.init(self.params.actor),
            "q1": self.tx.init(self.params.q1),
            "q2": self.tx.init(self.params.q2),
            "alpha": self.tx.init(self.params.log_alpha),
        }
        self._rng_key = jax.random.PRNGKey(seed + 7)
        self._update = self._build_update()

    # -- policy math ---------------------------------------------------------

    @staticmethod
    def _dist(actor_params, obs, action_size, lo=-20.0, hi=2.0):
        import jax.numpy as jnp

        out = _mlp_apply(actor_params, obs)
        mu, log_std = out[..., :action_size], out[..., action_size:]
        return mu, jnp.clip(log_std, lo, hi)

    def _sample_action(self, actor_params, obs, key):
        """Reparameterized tanh-Gaussian sample + log-prob (with the tanh
        Jacobian correction)."""
        import jax
        import jax.numpy as jnp

        mu, log_std = self._dist(actor_params, obs, self.action_size)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        a = jnp.tanh(pre)
        logp = (-0.5 * (eps**2 + 2 * log_std + math.log(2 * math.pi))
                ).sum(-1)
        logp -= jnp.log(1 - a**2 + 1e-6).sum(-1)
        return a * self.scale + self.bias, logp

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        gamma, tau, tx = self.gamma, self.tau, self.tx
        tgt_ent, scale, bias = self.target_entropy, self.scale, self.bias

        def q_apply(qp, obs, act):
            return _mlp_apply(qp, jnp.concatenate(
                [obs, (act - bias) / scale], -1))[..., 0]

        from ..devtools import jitguard

        jitguard.register_program("sac_update")

        def update(params: SACParams, opt_state, batch, key):
            # Trace-time only: joins the recompile sentinel (RT_DEBUG_JIT).
            jitguard.bump("sac_update", jitguard.signature_of(batch))
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(params.log_alpha)

            # Critic target: r + gamma (1-d) [min Q'(s', a') - alpha logp].
            next_a, next_logp = self._sample_action(params.actor,
                                                    batch["next_obs"], k1)
            q_next = jnp.minimum(
                q_apply(params.q1_target, batch["next_obs"], next_a),
                q_apply(params.q2_target, batch["next_obs"], next_a),
            ) - alpha * next_logp
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * q_next
            target = jax.lax.stop_gradient(target)

            def q_loss(qp):
                return ((q_apply(qp, batch["obs"], batch["actions"])
                         - target) ** 2).mean()

            q1_l, g1 = jax.value_and_grad(q_loss)(params.q1)
            q2_l, g2 = jax.value_and_grad(q_loss)(params.q2)
            up1, os_q1 = tx.update(g1, opt_state["q1"], params.q1)
            up2, os_q2 = tx.update(g2, opt_state["q2"], params.q2)
            q1_new = optax.apply_updates(params.q1, up1)
            q2_new = optax.apply_updates(params.q2, up2)

            # Actor: maximize min Q(s, pi(s)) - alpha logp.
            def actor_loss(ap):
                a, logp = self._sample_action(ap, batch["obs"], k2)
                q = jnp.minimum(q_apply(q1_new, batch["obs"], a),
                                q_apply(q2_new, batch["obs"], a))
                return (alpha * logp - q).mean(), logp

            (a_l, logp), ga = jax.value_and_grad(
                actor_loss, has_aux=True)(params.actor)
            upa, os_a = tx.update(ga, opt_state["actor"], params.actor)
            actor_new = optax.apply_updates(params.actor, upa)

            # Temperature: drive E[-logp] toward the target entropy.
            def alpha_loss(log_alpha):
                return -(jnp.exp(log_alpha)
                         * jax.lax.stop_gradient(logp + tgt_ent)).mean()

            al_l, gal = jax.value_and_grad(alpha_loss)(params.log_alpha)
            upal, os_al = tx.update(gal, opt_state["alpha"],
                                    params.log_alpha)
            log_alpha_new = optax.apply_updates(params.log_alpha, upal)

            # Polyak targets.
            q1_t = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                params.q1_target, q1_new)
            q2_t = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                params.q2_target, q2_new)
            new_params = SACParams(actor_new, q1_new, q2_new, q1_t, q2_t,
                                   log_alpha_new)
            new_os = {"actor": os_a, "q1": os_q1, "q2": os_q2,
                      "alpha": os_al}
            aux = {"critic_loss": q1_l + q2_l, "actor_loss": a_l,
                   "alpha": alpha, "entropy": -logp.mean()}
            return new_params, new_os, aux

        return jax.jit(update)

    # -- API -----------------------------------------------------------------

    def act(self, obs: np.ndarray, *, deterministic: bool = False):
        """Host-side action selection for env runners."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_act_fn"):
            def act_fn(actor, obs, key, det):
                mu, log_std = self._dist(actor, obs, self.action_size)
                eps = jax.random.normal(key, mu.shape)
                pre = jnp.where(det, mu, mu + jnp.exp(log_std) * eps)
                return jnp.tanh(pre) * self.scale + self.bias

            self._act_fn = jax.jit(act_fn, static_argnames=("det",))
        import jax

        self._rng_key, sub = jax.random.split(self._rng_key)
        return np.asarray(self._act_fn(self.params.actor, obs, sub,
                                       deterministic))

    def update_from_batch(self, batch: Dict[str, np.ndarray]):
        import jax
        import jax.numpy as jnp

        self._rng_key, sub = jax.random.split(self._rng_key)
        mb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, mb, sub)
        return {k: float(v) for k, v in aux.items()}

    def get_actor_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params.actor)


@ray_tpu.remote
class ContinuousEnvRunner:
    """Vectorized continuous-action rollout actor (SAC's off-policy runner;
    reference: single_agent_env_runner used by SAC with a connector turning
    episodes into transitions)."""

    def __init__(self, env_cls, num_envs: int, *, action_size: int,
                 scale: float, bias: float, seed: int = 0):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.envs = [env_cls(seed=seed * 1000 + i) for i in range(num_envs)]
        self.obs = np.stack([e.reset() for e in self.envs])
        self.num_envs = num_envs
        self.action_size = action_size
        self.scale = scale
        self.bias = bias
        self._rng = np.random.default_rng(seed + 1)
        self._actor = None
        self._fwd = None
        self.episode_returns = np.zeros(num_envs)
        self.completed: List[float] = []

    def set_actor_weights(self, weights, log_std_clip=(-20.0, 2.0)) -> bool:
        import jax
        import jax.numpy as jnp

        self._actor = jax.tree.map(jnp.asarray, weights)
        if self._fwd is None:
            lo, hi = log_std_clip
            asize, scale, bias = self.action_size, self.scale, self.bias

            def fwd(actor, obs, eps):
                out = _mlp_apply(actor, obs)
                mu, log_std = out[..., :asize], jnp.clip(
                    out[..., asize:], lo, hi)
                return jnp.tanh(mu + jnp.exp(log_std) * eps) * scale + bias

            self._fwd = jax.jit(fwd)
        return True

    def sample_transitions(self, num_steps: int,
                           random_actions: bool = False):
        N = self.num_envs
        D = self.obs.shape[1]
        obs_b = np.empty((num_steps, N, D), np.float32)
        next_b = np.empty((num_steps, N, D), np.float32)
        act_b = np.empty((num_steps, N, self.action_size), np.float32)
        rew_b = np.empty((num_steps, N), np.float32)
        done_b = np.zeros((num_steps, N), np.float32)
        for t in range(num_steps):
            if random_actions or self._actor is None:
                acts = self._rng.uniform(
                    self.bias - self.scale, self.bias + self.scale,
                    (N, self.action_size)).astype(np.float32)
            else:
                eps = self._rng.standard_normal(
                    (N, self.action_size)).astype(np.float32)
                acts = np.asarray(self._fwd(self._actor, self.obs, eps))
            obs_b[t] = self.obs
            act_b[t] = acts
            for i, env in enumerate(self.envs):
                o, r, term, trunc = env.step(acts[i])
                rew_b[t, i] = r
                self.episode_returns[i] += r
                # done=termination only; truncation still bootstraps.
                done_b[t, i] = float(term)
                next_b[t, i] = o
                if term or trunc:
                    self.completed.append(float(self.episode_returns[i]))
                    self.episode_returns[i] = 0.0
                    o = env.reset()
                self.obs[i] = o
        out, self.completed = self.completed, []
        return {
            "obs": obs_b.reshape(-1, D),
            "next_obs": next_b.reshape(-1, D),
            "actions": act_b.reshape(-1, self.action_size),
            "rewards": rew_b.reshape(-1),
            "dones": done_b.reshape(-1),
            "episode_returns": np.asarray(out),
        }


class SACConfig:
    def __init__(self):
        self.env_cls = PendulumEnv
        self.num_env_runners = 1
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 32
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.hidden = 256
        self.buffer_size = 100_000
        self.batch_size = 256
        self.updates_per_round = 16
        self.warmup_steps = 1_000
        self.seed = 0

    def environment(self, env_cls) -> "SACConfig":
        self.env_cls = env_cls
        return self

    def training(self, **kwargs) -> "SACConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    """Off-policy loop: sample transitions -> replay buffer -> k jitted
    updates -> actor-weight sync (reference: sac.py training_step)."""

    def __init__(self, config: SACConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        env = config.env_cls()
        obs_size = env.observation_size
        self.learner = SACLearner(
            obs_size, env.action_size,
            action_low=env.action_low, action_high=env.action_high,
            lr=config.lr, gamma=config.gamma, tau=config.tau,
            hidden=config.hidden, seed=config.seed,
        )
        scale = (env.action_high - env.action_low) / 2.0
        bias = (env.action_high + env.action_low) / 2.0
        self.runners = [
            ContinuousEnvRunner.remote(
                config.env_cls, config.num_envs_per_runner,
                action_size=env.action_size, scale=scale, bias=bias,
                seed=config.seed + i)
            for i in range(config.num_env_runners)
        ]
        self._rng = np.random.default_rng(config.seed)
        self._buffer: Dict[str, np.ndarray] = {}
        self._buf_n = 0
        self._sync_weights()
        self.iteration = 0
        self.total_env_steps = 0
        self._recent: List[float] = []

    def _sync_weights(self):
        ref = ray_tpu.put(self.learner.get_actor_weights())
        ray_tpu.get([r.set_actor_weights.remote(ref) for r in self.runners])

    def _add_to_buffer(self, batch):
        n = len(batch["rewards"])
        cap = self.config.buffer_size
        if not self._buffer:
            self._buffer = {
                k: np.empty((cap, *v.shape[1:]), v.dtype)
                for k, v in batch.items() if k != "episode_returns"
            }
            self._pos = 0
        for k, buf in self._buffer.items():
            data = batch[k]
            idx = (self._pos + np.arange(n)) % cap
            buf[idx] = data
        self._pos = (self._pos + n) % cap
        self._buf_n = min(self._buf_n + n, cap)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        warmup = self.total_env_steps < cfg.warmup_steps
        samples = ray_tpu.get([
            r.sample_transitions.remote(cfg.rollout_fragment_length,
                                        random_actions=warmup)
            for r in self.runners
        ])
        for s in samples:
            self._recent.extend(s.pop("episode_returns").tolist())
            self._add_to_buffer(s)
            self.total_env_steps += len(s["rewards"])
        self._recent = self._recent[-50:]

        metrics: Dict[str, float] = {}
        if self._buf_n >= cfg.batch_size and not warmup:
            for _ in range(cfg.updates_per_round):
                idx = self._rng.integers(0, self._buf_n, cfg.batch_size)
                mb = {k: v[idx] for k, v in self._buffer.items()}
                metrics = self.learner.update_from_batch(mb)
            self._sync_weights()
        self.iteration += 1
        wall = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self.total_env_steps,
            "episode_return_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "env_steps_per_sec": (
                len(samples) * cfg.rollout_fragment_length
                * cfg.num_envs_per_runner / max(wall, 1e-9)),
            **metrics,
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
